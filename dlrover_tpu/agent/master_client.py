"""MasterClient: the agent/worker-side control-plane client.

Equivalent capability: reference dlrover/python/elastic_agent/
master_client.py:50 — singleton client with retry, covering the full API:
tasks/shards, rendezvous join/comm-world, network status, parallel config,
heartbeats, kv-store, metrics, failure reports.
"""

from __future__ import annotations

import os
import socket
import threading
import time

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.constants import (
    JobConstant,
    NodeEnv,
    NodeType,
    RendezvousName,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.rpc import RpcClient

logger = get_logger(__name__)

# Ride-through knobs (agent-side master-failover tolerance).
ENV_RIDE_THROUGH = "DLROVER_MASTER_RIDE_THROUGH"  # seconds
ENV_RIDE_POLL = "DLROVER_MASTER_RIDE_POLL"        # probe interval


def resolve_master_addr(default: str = "") -> str:
    """The master's CURRENT address: the address file (written by
    ``master.main --addr-file``, atomically re-written on restart) wins
    over the launch-time env var, which wins over ``default``."""
    path = os.environ.get(NodeEnv.DLROVER_MASTER_ADDR_FILE, "")
    if path:
        try:
            with open(path) as f:
                addr = f.read().strip()
            if addr:
                return addr
        except OSError:
            pass
    return os.environ.get(NodeEnv.DLROVER_MASTER_ADDR, "") or default


class MasterClient:
    _instance = None
    _instance_lock = threading.Lock()

    def __init__(
        self, master_addr: str, node_id: int, node_type: str,
        addr_resolver=None,
    ):
        self._addr = master_addr
        self._node_id = node_id
        self._node_type = node_type
        self._rpc = RpcClient(
            master_addr,
            addr_resolver=(
                addr_resolver
                if addr_resolver is not None
                else lambda: resolve_master_addr(master_addr)
            ),
        )
        self._host = socket.gethostname()
        try:
            self._host_ip = socket.gethostbyname(self._host)
        except OSError:
            self._host_ip = "127.0.0.1"

    # ------------------------------------------------------------ plumbing

    @property
    def master_addr(self) -> str:
        # the RpcClient's view: follows resolver-driven re-resolution
        return self._rpc.addr

    @property
    def host_ip(self) -> str:
        return self._host_ip

    @property
    def node_id(self) -> int:
        return self._node_id

    def _get(self, message, retries: int | None = None):
        # retries=None -> the shared RetryPolicy decides (DLROVER_RPC_*
        # env, one place); explicit retries = fail-fast best-effort calls
        return self._rpc.get(self._node_type, self._node_id, message, retries)

    def _report(self, message, retries: int | None = None) -> bool:
        return self._rpc.report(
            self._node_type, self._node_id, message, retries
        )

    def ping(self) -> bool:
        return self._rpc.ping()

    def close(self):
        self._rpc.close()

    # ------------------------------------------------- master ride-through

    def await_master(
        self, timeout: float | None = None, poll: float | None = None
    ) -> bool:
        """Bounded ride-through for an unreachable master.

        Ordinary RPC exhaustion (a reachable master answering with
        errors) surfaces as RuntimeError and is NOT what this handles;
        this is for transport-level loss — the coordinator died or
        moved. Each probe closes the cached socket so the next connect
        re-resolves the address (env / address file), then pings.
        Returns True the moment the master (old or restarted) answers;
        False when the budget runs out — the caller decides whether to
        keep training and retry or give up."""
        if timeout is None:
            timeout = float(os.environ.get(
                ENV_RIDE_THROUGH,
                str(JobConstant.MASTER_RIDE_THROUGH_DEFAULT),
            ))
        if poll is None:
            poll = float(os.environ.get(ENV_RIDE_POLL, "2.0"))
        deadline = time.monotonic() + timeout
        while True:
            self._rpc.close()  # force re-resolve + reconnect
            if self.ping():
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(min(poll, max(deadline - time.monotonic(), 0.0)))

    # ------------------------------------------------------- data sharding

    def report_dataset_shard_params(
        self,
        batch_size: int,
        num_epochs: int,
        dataset_size: int,
        shuffle: bool = False,
        num_minibatches_per_shard: int = 2,
        dataset_name: str = "train",
        task_type: str = "training",
        storage_type: str = "",
        dataset_type: str = "table",
    ) -> bool:
        return self._report(
            msg.DatasetShardParams(
                batch_size=batch_size,
                num_epochs=num_epochs,
                dataset_size=dataset_size,
                shuffle=shuffle,
                num_minibatches_per_shard=num_minibatches_per_shard,
                dataset_name=dataset_name,
                task_type=task_type,
                storage_type=storage_type,
                dataset_type=dataset_type,
            )
        )

    def get_task(self, dataset_name: str) -> msg.Task:
        task = self._get(msg.TaskRequest(dataset_name=dataset_name))
        return task if task is not None else msg.Task()

    def report_task_result(
        self, dataset_name: str, task_id: int, err_message: str = ""
    ) -> bool:
        return self._report(
            msg.TaskResult(
                dataset_name=dataset_name,
                task_id=task_id,
                err_message=err_message,
            )
        )

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        ckpt = self._get(msg.ShardCheckpointRequest(dataset_name=dataset_name))
        return ckpt.content if ckpt else ""

    def report_shard_checkpoint(self, content: str) -> bool:
        return self._report(msg.ShardCheckpoint(content=content))

    # ----------------------------------------------------------- rendezvous

    def join_rendezvous(
        self, node_rank: int, local_world_size: int, rdzv_name: str,
        verified_ckpt_step: int = -1, verified_ckpt_steps=None,
        probe_report=None,
    ) -> bool:
        return self._report(
            msg.JoinRendezvousRequest(
                node_id=self._node_id,
                node_rank=node_rank,
                local_world_size=local_world_size,
                rdzv_name=rdzv_name,
                node_ip=self._host_ip,
                verified_ckpt_step=verified_ckpt_step,
                verified_ckpt_steps=list(verified_ckpt_steps or ()),
                # the hardware probe's per-leg timings; empty = no
                # probe ran, the master's gate admits (old behavior)
                probe_report=dict(probe_report or {}),
            )
        )

    def report_verified_steps(
        self, node_rank: int, steps,
        rdzv_name: str = RendezvousName.ELASTIC_TRAINING,
    ) -> bool:
        """Refresh this node's restorable-step set without joining —
        used when re-registering after a master failover (a join would
        dissolve the restored round and restart healthy workers)."""
        return self._report(
            msg.VerifiedStepsReport(
                node_rank=node_rank,
                rdzv_name=rdzv_name,
                steps=[int(s) for s in (steps or ())],
            ),
            retries=2,
        )

    def drain_node(self, node_rank: int) -> bool:
        """Graceful scale-in announcement: ``node_rank`` leaves the job
        with its host still alive, so survivors get a "drained"
        departure (reshape in place, shards readable device-to-device)
        instead of the "dead" a heartbeat timeout would record. Called
        by a platform scaler or by a preempted node's agent ahead of
        its own shutdown."""
        return self._report(
            msg.DrainNodeRequest(node_rank=node_rank)
        )

    def report_preempt_notice(
        self, node_rank: int, deadline: float, lead_s: float = 0.0,
    ) -> msg.PreemptNoticeDirective:
        """Relay an announced preemption (this host dies at
        ``deadline``) and fetch the brain's directive. Fail-fast: the
        lead window is short, and an unreachable master just means the
        unannounced-kill fallback path — never a stall."""
        res = self._get(
            msg.PreemptNoticeRequest(
                node_rank=node_rank, deadline=deadline, lead_s=lead_s,
            ),
            retries=2,
        )
        return res if res is not None else msg.PreemptNoticeDirective()

    def get_comm_world(self, rdzv_name: str, node_rank: int):
        world: msg.CommWorld = self._get(
            msg.CommWorldRequest(node_id=node_rank, rdzv_name=rdzv_name)
        )
        return world

    def num_nodes_waiting(self, rdzv_name: str) -> int:
        res: msg.WaitingNodeNum = self._get(
            msg.WaitingNodeNumRequest(rdzv_name=rdzv_name)
        )
        return res.waiting_num if res else 0

    # --------------------------------------------------- node health check

    def report_node_check_result(
        self, node_rank: int, normal: bool, elapsed: float
    ) -> bool:
        return self._report(
            msg.NodeCheckResultRequest(
                node_id=node_rank, normal=normal, elapsed_time=elapsed
            )
        )

    def check_network_ready(self) -> msg.NetworkCheckResult:
        return self._get(msg.NetworkReadyRequest())

    def get_node_health(self, node_rank: int) -> msg.NodeHealthVerdict:
        """This host's standing health-gate verdict — polled while a
        join has been acked but no world forms, to tell "round still
        filling" apart from "parked in quarantine"."""
        res = self._get(
            msg.NodeHealthRequest(node_rank=node_rank), retries=1
        )
        return res if res is not None else msg.NodeHealthVerdict()

    def report_probe(self, node_rank: int, report: dict) -> bool:
        """Ship an in-band re-probe report to the fingerprint store.
        Best-effort: a dropped sample just waits for the next window."""
        return self._report(
            msg.HostProbeReport(
                node_rank=node_rank, report=dict(report or {})
            ),
            retries=1,
        )

    def check_straggler(self) -> msg.NetworkCheckResult:
        return self._get(msg.StragglerExistRequest())

    def get_diagnosis(self) -> msg.DiagnosisResult:
        """The master's current runtime verdicts (stragglers + hangs).
        Best-effort fail-fast poll like the stats reports."""
        res = self._get(msg.DiagnosisRequest(node_rank=self._node_id),
                        retries=1)
        return res if res is not None else msg.DiagnosisResult()

    # -------------------------------------------------- deep captures

    def request_capture(
        self, node_rank: int = -1, steps: int = 0,
        reason: str = "operator",
    ) -> msg.ProfileCaptureAck:
        """Ask the master's CaptureManager for a deep capture of
        ``node_rank`` (the obs_report --capture front door)."""
        res = self._get(msg.ProfileCaptureRequest(
            node_rank=node_rank, steps=steps, reason=reason,
        ))
        return res if res is not None else msg.ProfileCaptureAck(
            reason="no response"
        )

    def list_captures(self) -> list:
        res: msg.CaptureList = self._get(msg.CaptureListRequest())
        return list(res.captures) if res else []

    def report_capture_result(
        self, capture_id: str, node_rank: int, ok: bool,
        artifact: str = "", summary: dict | None = None,
        error: str = "",
    ) -> bool:
        """Land a capture outcome on the master ledger (fail-fast:
        the directive re-serves on the next diagnosis poll if this
        report is lost)."""
        return self._report(
            msg.CaptureResultReport(
                capture_id=capture_id,
                node_rank=node_rank,
                ok=ok,
                artifact=artifact,
                summary=dict(summary or {}),
                error=error,
            ),
            retries=2,
        )

    def report_failure(
        self, error_data: str, level: str, restart_count: int = 0
    ) -> bool:
        return self._report(
            msg.NodeFailure(
                node_id=self._node_id,
                error_data=error_data,
                level=level,
                restart_count=restart_count,
            )
        )

    # ------------------------------------------------- heartbeat & metrics

    def report_heart_beat(self, timestamp=None) -> msg.HeartbeatResponse:
        resp = self._get(
            msg.HeartBeat(
                node_id=self._node_id, timestamp=timestamp or time.time()
            )
        )
        return resp if resp is not None else msg.HeartbeatResponse()

    def report_used_resource(
        self, cpu_percent: float, memory_mb: int, tpu_stats=None
    ) -> bool:
        return self._report(
            msg.ResourceStats(
                node_id=self._node_id,
                cpu_percent=cpu_percent,
                memory_mb=memory_mb,
                tpu_stats=tpu_stats or [],
            ),
            retries=1,
        )

    def report_global_step(self, step: int, timestamp=None) -> bool:
        return self._report(
            msg.GlobalStep(
                step=step, timestamp=timestamp or time.time()
            ),
            retries=1,
        )

    def report_telemetry(self, snapshot: dict) -> bool:
        """Ship a telemetry registry snapshot (cumulative, idempotent);
        best-effort like the other stats reports."""
        return self._report(
            msg.TelemetrySnapshot(
                node_id=self._node_id, payload=snapshot
            ),
            retries=1,
        )

    def get_telemetry_report(self) -> dict:
        """The master's merged job view (goodput ledger + timeline)."""
        res: msg.TelemetryReport = self._get(msg.TelemetryReportRequest())
        return res.payload if res else {}

    def query_metrics(
        self,
        name: str,
        source: str = "",
        resolution: str = "raw",
        since: float = 0.0,
        limit: int = 0,
    ) -> list:
        """Time series from the master's tiered metrics store (the
        live metrics plane); see ``MetricsQueryRequest``."""
        res: msg.MetricsSeries = self._get(
            msg.MetricsQueryRequest(
                name=name, source=source, resolution=resolution,
                since=since, limit=limit,
            )
        )
        return res.series if res else []

    # ------------------------------------------------------------- serving

    def serve_submit(
        self,
        request_id: str,
        prompt,
        max_new_tokens: int = 16,
        temperature: float = 0.0,
        eos_id: int = -1,
    ) -> bool:
        """Submit one generation request to the master's serving
        ledger (idempotent by request_id — retries after a dropped ack
        cannot double-serve)."""
        return self._report(
            msg.ServeSubmitRequest(
                request_id=request_id,
                prompt=list(prompt),
                max_new_tokens=max_new_tokens,
                temperature=temperature,
                eos_id=eos_id,
            )
        )

    def serve_lease(self, max_requests: int) -> list:
        """Pull up to ``max_requests`` queued requests for this decode
        worker (payload dicts; the lease deadline lives on the
        master)."""
        res: msg.ServeLease = self._get(
            msg.ServeLeaseRequest(
                node_rank=self._node_id, max_requests=max_requests
            )
        )
        return list(res.requests) if res else []

    def serve_report_result(self, request_id: str, tokens,
                            finish_reason: str = "") -> bool:
        return self._report(
            msg.ServeResultReport(
                request_id=request_id,
                node_rank=self._node_id,
                tokens=list(tokens),
                finish_reason=finish_reason,
            )
        )

    def serve_status(self) -> dict:
        res: msg.ServeStatus = self._get(msg.ServeStatusRequest())
        return dict(res.summary) if res else {}

    def serve_fetch(self, request_id: str) -> msg.ServeResult:
        return self._get(msg.ServeFetchRequest(request_id=request_id))

    def report_node_meta(
        self, node_rank: int, addr: str, tpu_chips: int = 0
    ) -> bool:
        return self._report(
            msg.NodeMeta(
                node_type=self._node_type,
                node_id=self._node_id,
                node_rank=node_rank,
                addr=addr,
                tpu_chips=tpu_chips,
            )
        )

    # -------------------------------------------------------------- config

    def report_rdzv_params(self, min_nodes: int, max_nodes: int,
                           waiting_timeout: float = 30.0,
                           node_unit: int = 1) -> bool:
        return self._report(msg.RdzvParamsReport(
            min_nodes=min_nodes, max_nodes=max_nodes,
            waiting_timeout=waiting_timeout, node_unit=node_unit,
        ))

    def feed_streaming_dataset(self, dataset_name: str, count: int,
                               end: bool = False) -> bool:
        return self._report(msg.StreamingFeed(
            dataset_name=dataset_name, count=count, end=end
        ))

    def get_ps_version(self, version_type: str = "global") -> int:
        resp = self._get(msg.PsVersionRequest(version_type=version_type))
        return resp.version if resp is not None else 0

    def report_ps_version(self, version: int,
                          version_type: str = "local") -> bool:
        return self._report(msg.PsVersionReport(
            version_type=version_type, version=version
        ))

    def get_paral_config(self) -> msg.ParallelConfig:
        # best-effort tuning poll: fail fast and let the tuner's
        # NonCriticalGuard degrade, like the stats reports above
        return self._get(msg.ParallelConfigRequest(), retries=2)

    def report_elastic_run_config(self, configs: dict) -> bool:
        return self._report(msg.ElasticRunConfig(configs=configs))

    def get_elastic_run_config(self, retries: int | None = None) -> dict:
        # explicit retries = fail-fast advisory polls (the trainer's
        # cadence adoption must never stall a step boundary)
        res: msg.ElasticRunConfig = self._get(
            msg.ElasticRunConfigRequest(), retries
        )
        return res.configs if res else {}

    # ------------------------------------------------------------ kv store

    def kv_store_set(self, key: str, value: bytes) -> bool:
        return self._report(msg.KeyValuePair(key=key, value=value))

    def kv_store_get(self, key: str) -> bytes:
        pair: msg.KeyValuePair = self._get(msg.KeyValueGetRequest(key=key))
        return pair.value if pair else b""

    def kv_store_add(self, key: str, delta: int) -> int:
        res: msg.KeyValueAddResult = self._get(
            msg.KeyValueAddRequest(key=key, delta=delta)
        )
        return res.value if res else 0

    # ----------------------------------------------------------- ckpt sync

    def report_ckpt_ready(
        self, step: int, group: str, world: int
    ) -> bool:
        return self._report(
            msg.CheckpointReadyRequest(
                node_id=self._node_id,
                step=step,
                group=group,
                world=world,
            )
        )

    def check_ckpt_barrier(
        self, step: int, group: str, world: int
    ) -> tuple[bool, bool]:
        """-> (passed, aborted)"""
        res: msg.BarrierResponse = self._get(
            msg.CheckpointReadyRequest(
                node_id=self._node_id, step=step, group=group, world=world
            )
        )
        if not res:
            return False, False
        return res.passed, getattr(res, "aborted", False)

    def report_ckpt_skip(self, step: int, group: str) -> bool:
        """Tell peers this host is sitting this save out."""
        return self._report(
            msg.CheckpointReadyRequest(
                node_id=self._node_id, step=step, group=group,
                ready=False,
            )
        )

    def sync_checkpoint(self, step: int) -> bool:
        return self._report(
            msg.CheckpointSyncRequest(node_id=self._node_id, step=step)
        )

    # ------------------------------------------------------------ barriers

    def join_sync(self, sync_name: str) -> bool:
        return self._report(
            msg.SyncJoin(
                sync_name=sync_name,
                node_id=self._node_id,
                node_type=self._node_type,
            )
        )

    def sync_finished(self, sync_name: str) -> bool:
        return self._report(msg.SyncFinish(sync_name=sync_name))

    def barrier(self, sync_name: str, notify: bool = False) -> bool:
        res = self._get(
            msg.SyncBarrierRequest(sync_name=sync_name, notify=notify)
        )
        return res.success if res else False

    def report_job_end(self, success: bool, reason: str = "") -> bool:
        return self._report(
            msg.JobEnd(node_id=self._node_id, success=success, reason=reason)
        )

    # ---------------------------------------------------------- singleton

    @classmethod
    def singleton_instance(cls) -> "MasterClient | None":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = build_master_client()
        return cls._instance

    @classmethod
    def reset_singleton(cls, client: "MasterClient | None" = None):
        with cls._instance_lock:
            cls._instance = client


def build_master_client(
    master_addr: str | None = None, node_id: int | None = None
) -> MasterClient | None:
    """Build from env contract (reference master_client.py:408)."""
    addr = master_addr or os.environ.get(NodeEnv.DLROVER_MASTER_ADDR, "")
    if not addr:
        return None
    nid = (
        node_id
        if node_id is not None
        else int(os.environ.get(NodeEnv.NODE_RANK, "0"))
    )
    node_type = os.environ.get(NodeEnv.NODE_TYPE, NodeType.WORKER)
    return MasterClient(addr, nid, node_type)
