"""Flash Checkpoint: shared-memory layout + agent-side async saver daemon.

Equivalent capability: reference dlrover/python/elastic_agent/torch/
ckpt_saver.py — SharedMemoryHandler (:209, tensor-meta dict + shm
buffer), AsyncCheckpointSaver (:342) with its factory queue (:406),
shm->storage event loop (:506), per-shard save (:533),
save_shm_to_storage on failure/SIGTERM (:622), signal handlers (:468);
CommonDirCheckpointSaver (:761), TempDirCheckpointSaver (:908).

TPU redesign: the training process is a JAX host process whose
addressable array shards are written (async HBM->host) into a
POSIX shm segment; this module is deliberately **jax-free** — the agent
daemon only moves bytes between shm and storage, so it keeps working
while the training process is dead (that is the whole point: the
checkpoint survives worker crashes and persists in the background).

Shm layout:  [u64 meta_len][pickled meta][raw tensor bytes...]
Meta: {"step": int, "paths": [leaf names], "leaves": [LeafMeta], ...}
"""

from __future__ import annotations

import json
import os
import pickle
import queue as _queue
import signal
import threading
import time
from dataclasses import dataclass, field

from dlrover_tpu.common import telemetry
from dlrover_tpu.common.chaos import chaos_transform
from dlrover_tpu.common.constants import CheckpointConstant
from dlrover_tpu.common.ipc import (
    SharedLock,
    SharedQueue,
    get_or_create_shm,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.storage import PosixDiskStorage

logger = get_logger(__name__)

_META_LEN_SIZE = 8

SAVER_FACTORY_QUEUE = "ckpt_factory"


def _pid_alive(pid: str) -> bool:
    """True if the process that acquired a SharedLock still exists."""
    try:
        os.kill(int(pid), 0)
        return True
    except (ValueError, ProcessLookupError):
        return False
    except PermissionError:
        return True


def shm_name(local_rank: int = 0) -> str:
    job = os.environ.get("ELASTIC_JOB_NAME", "local")
    return f"dlrtpu_ckpt_{job}_{local_rank}"


def lock_name(local_rank: int = 0) -> str:
    return f"ckpt_shm_{local_rank}"


def event_queue_name(local_rank: int = 0) -> str:
    return f"ckpt_event_{local_rank}"


def persist_done_queue_name(local_rank: int = 0) -> str:
    """Agent -> worker persist-completion wakeups: the saver puts the
    persisted step here after the commit protocol, so the engine's
    ``wait_for_persist`` (and the trainer's final-save retry loop) wake
    on the event instead of quantizing end-of-run latency to a poll
    interval. The tracker file stays the source of truth — the queue is
    only a wakeup hint, bounded and droppable."""
    return f"ckpt_done_{local_rank}"


@dataclass
class LeafMeta:
    """One array (or array shard) in the shm buffer."""

    path: str = ""
    dtype: str = ""
    shape: tuple = ()
    offset: int = 0
    nbytes: int = 0
    # GSPMD sharding info: the global shape of the array and the index of
    # this host-local shard as ((start, stop) per dim); None => replicated
    global_shape: tuple | None = None
    index: tuple | None = None


@dataclass
class CheckpointMeta:
    step: int = 0
    leaves: list = field(default_factory=list)
    treedef: bytes = b""
    # which framework engine wrote it (replicated | sharded)
    engine: str = "replicated"
    host_rank: int = 0
    num_hosts: int = 1
    total_bytes: int = 0
    user_meta: dict = field(default_factory=dict)
    # CRC-32 of the persisted payload (set at persist time; -1 = absent).
    # Verified on read so a torn/corrupted shard file is rejected instead
    # of silently restoring garbage.
    payload_crc: int = -1


@dataclass
class SaveEvent:
    step: int = 0
    path: str = ""
    storage_type: str = "disk"  # "disk" persists; "memory" = shm only


class SharedMemoryHandler:
    """Reads/writes the checkpoint shm segment (usable from either side
    of the agent/worker boundary)."""

    def __init__(self, local_rank: int = 0):
        self._local_rank = local_rank
        self._shm = None

    @property
    def shm(self):
        return self._shm

    def _ensure(self, size: int):
        if self._shm is None or self._shm.size < size:
            if self._shm is not None:
                self._shm.close()
            self._shm = get_or_create_shm(
                shm_name(self._local_rank), size
            )
            if getattr(self._shm, "just_created", False):
                # A FRESH segment's pages fault in on first touch; left
                # to the copy loop that tax is paid inside the timed
                # save interleaved with the memcpy (the
                # ckpt_engine_cold_gbps vs warm gap). Fault them in NOW
                # with a dedicated page-touch pass — measurably ~4-6x
                # cheaper than faulting from inside a large memcpy even
                # single-threaded, and threaded on multi-core hosts.
                # The segment is new, so its contents are garbage by
                # contract (the touch writes zeros).
                try:
                    from dlrover_tpu import native as dlrtpu_native

                    dlrtpu_native.prefault(self._shm.buf)
                except Exception:  # noqa: BLE001 - prefault is an
                    # optimization; the copy path faults pages in anyway
                    pass

    def attach(self) -> bool:
        """Attach to an existing segment (agent side)."""
        try:
            self._shm = get_or_create_shm(shm_name(self._local_rank))
            return True
        except FileNotFoundError:
            return False

    def refresh(self):
        """Drop the cached mapping and re-attach: the worker may have
        unlinked+recreated the segment when the state dict grew, and a
        cached mapping would keep reading the stale bytes forever."""
        if self._shm is not None:
            self._shm.close()
            self._shm = None
        return self.attach()

    def write_meta_and_reserve(
        self, meta: CheckpointMeta, publish: bool = True
    ) -> memoryview:
        """Write the meta header and return a view over the tensor area.

        ``publish=False`` stages the meta but leaves the length prefix
        zeroed (readers see "no checkpoint") until :meth:`publish_meta`
        — two-phase commit for drains that fill the tensor area over a
        long window (chunked D2H): a preemption mid-drain must never
        leave a valid meta over partial bytes, or the failure-path
        save_shm_to_storage persists a torn snapshot and restore loads
        mixed-step weights. The prefix itself is invalidated FIRST in
        both modes so a crash between meta and data writes is also
        unreadable.
        """
        meta_bytes = pickle.dumps(meta)
        data_start = _META_LEN_SIZE + len(meta_bytes)
        total = data_start + meta.total_bytes
        self._ensure(total)
        buf = self._shm.buf
        buf[:_META_LEN_SIZE] = (0).to_bytes(_META_LEN_SIZE, "little")
        buf[_META_LEN_SIZE : data_start] = meta_bytes
        self._staged_meta_len = len(meta_bytes)
        if publish:
            self.publish_meta()
        return buf[data_start : data_start + meta.total_bytes]

    def publish_meta(self) -> None:
        """Commit a staged meta: the single prefix-word write makes the
        checkpoint visible atomically (readers re-validate by parsing)."""
        self._shm.buf[:_META_LEN_SIZE] = self._staged_meta_len.to_bytes(
            _META_LEN_SIZE, "little"
        )

    def read(self) -> tuple[CheckpointMeta, memoryview] | None:
        if self._shm is None and not self.attach():
            return None
        buf = self._shm.buf
        meta_len = int.from_bytes(buf[:_META_LEN_SIZE], "little")
        if meta_len == 0 or meta_len > self._shm.size:
            return None
        try:
            meta: CheckpointMeta = pickle.loads(
                bytes(buf[_META_LEN_SIZE : _META_LEN_SIZE + meta_len])
            )
        except Exception:  # noqa: BLE001 - partial/garbage header
            return None
        data_start = _META_LEN_SIZE + meta_len
        return meta, buf[data_start : data_start + meta.total_bytes]

    def get_checkpoint_step(self) -> int:
        result = self.read()
        return result[0].step if result else -1

    def no_checkpoint_state(self) -> bool:
        return self.read() is None

    def mark_empty(self):
        if self._shm is not None:
            self._shm.buf[:_META_LEN_SIZE] = (0).to_bytes(
                _META_LEN_SIZE, "little"
            )

    def close(self, unlink: bool = False):
        if self._shm is not None:
            self._shm.close()
            if unlink:
                try:
                    self._shm.unlink()
                except FileNotFoundError:
                    pass
            self._shm = None


# --------------------------------------------------------------------------
# storage file format: one file per host per step
# --------------------------------------------------------------------------


def host_shard_filename(host_rank: int) -> str:
    return f"host_{host_rank}.dlck"


def manifest_filename(host_rank: int) -> str:
    return f"host_{host_rank}.manifest.json"


# Slack appended to the pickled-meta slot so the header's byte length
# is fixed BEFORE the streaming crc lands in it: pickle ignores bytes
# after the STOP opcode, and an int's pickled width varies by value
# (BININT1 through LONG1 across the crc range) by at most a few bytes.
_META_CRC_SLACK = 16


def write_host_shard(
    storage, path: str, meta: CheckpointMeta, data
) -> tuple[int, int]:
    """Stream header + meta + payload; ``data`` may be a memoryview into
    shm — never copy the (multi-GB) payload into an intermediate blob.

    The payload CRC is stamped into the meta so restores detect torn or
    bit-rotted shard files. It is computed chunk-wise DURING the payload
    write (one traversal: the checksum of chunk i overlaps the disk
    write of chunks <= i) instead of in a pre-pass over the whole
    payload; the header lands last in the invisible temp file, its byte
    length pinned up front by padding the pickled meta (readers stop at
    pickle's STOP opcode, so the pad is compatible with every existing
    reader). Returns (payload_crc, payload_nbytes) — the INTENDED
    values, stamped into the sidecar manifest before any fault (chaos
    tear/bitflip, a real crash mid-write) can corrupt the on-disk
    bytes."""
    from dlrover_tpu import native as dlrtpu_native

    payload_nbytes = (
        data.nbytes if isinstance(data, memoryview) else len(data)
    )
    # fault site: tear (truncate mid-shard) or bit-flip the persisted
    # payload — the crc must describe the INTENDED bytes while the
    # corrupted ones hit the disk, so a fired transform forces the
    # two-pass shape (crc over the original, write the corrupted)
    transformed = chaos_transform(
        "ckpt.write", data, step=meta.step, path=path
    )
    if transformed is not data:
        meta.payload_crc = dlrtpu_native.crc32_parallel(data)
        meta_bytes = pickle.dumps(meta)
        storage.write_parts(
            [
                len(meta_bytes).to_bytes(_META_LEN_SIZE, "little"),
                meta_bytes,
                transformed,
            ],
            path,
        )
        return meta.payload_crc, payload_nbytes

    meta.payload_crc = 0
    meta_len = len(pickle.dumps(meta)) + _META_CRC_SLACK

    def make_header(crc: int) -> bytes:
        meta.payload_crc = crc
        meta_bytes = pickle.dumps(meta)
        assert len(meta_bytes) <= meta_len, "crc widened meta past slack"
        meta_bytes += b"\x00" * (meta_len - len(meta_bytes))
        return (
            meta_len.to_bytes(_META_LEN_SIZE, "little") + meta_bytes
        )

    crc = storage.write_payload_with_header(
        path, _META_LEN_SIZE + meta_len, make_header, data
    )
    return crc, payload_nbytes


def write_shard_manifest(
    storage, step_dir: str, shard_id: int, step: int,
    payload_crc: int, payload_nbytes: int, engine: str,
) -> None:
    """Per-shard checksum manifest, written right after its shard and
    strictly BEFORE the atomic step-dir rename / tracker update, so a
    restore can verify integrity without trusting the shard's own
    (possibly torn) embedded meta."""
    entry = {
        "format": 1,
        "step": step,
        "file": host_shard_filename(shard_id),
        "payload_crc": payload_crc,
        "payload_nbytes": payload_nbytes,
        "engine": engine,
    }
    blob = json.dumps(entry, sort_keys=True).encode()
    blob = chaos_transform("ckpt.manifest", blob, step=step)
    storage.write(blob, os.path.join(step_dir, manifest_filename(shard_id)))


_READ_CHUNK = 8 << 20


def _file_payload_crc(path: str, payload_start: int) -> tuple[int, int]:
    """(crc32, nbytes) of the payload region, chunked (bounded memory).
    The chunk buffer comes from the host arena and is read INTO, so a
    full-checkpoint verify allocates nothing per chunk."""
    from dlrover_tpu import native as dlrtpu_native
    from dlrover_tpu.common.arena import get_arena

    crc = 0
    nbytes = 0
    with get_arena().lease(_READ_CHUNK) as lease, open(path, "rb") as f:
        buf = lease.view
        f.seek(payload_start)
        while True:
            got = f.readinto(buf)
            if not got:
                break
            crc = dlrtpu_native.crc32(buf[:got], crc)
            nbytes += got
    return crc, nbytes


_VERIFIED_MARKER = ".verified"


def verify_step_dir(step_dir: str, deep: bool = True) -> tuple[bool, str]:
    """Integrity-verify every shard of a persisted step directory.

    Returns (ok, reason). A shard verifies against its sidecar manifest
    (payload size + crc recomputed from the actual bytes); a legacy
    shard without a manifest falls back to the crc embedded in its own
    meta. Any torn, bit-flipped, unreadable, or manifest-corrupted
    shard fails the WHOLE directory — restore then falls back to the
    next-newest verified checkpoint instead of loading garbage.

    ``deep=False`` runs structural + size checks only (catches torn
    writes, unreadable metas, corrupt manifests) and skips the payload
    CRC: for the EAGER load path, whose ``read_host_shard`` re-verifies
    every payload's embedded crc anyway — a deep verify there would
    read and checksum multi-GB payloads twice. The targeted shard-wise
    path performs crc-less slice reads, so it must verify deep.

    Deep CRC results are cached in a ``.verified`` marker inside the
    step dir (shard files are immutable once committed): the first
    verifier pays the full read; later ones — other hosts of a shared
    filesystem, repeat restores — only size-check, so an 8-host restore
    does not read the whole checkpoint 8 times over. Trade: bit-rot
    striking AFTER a successful deep verify (same size) is not
    re-detected through the cache."""
    if not os.path.isdir(step_dir):
        return False, "not a directory"
    try:
        names = sorted(os.listdir(step_dir))
    except OSError as e:
        return False, f"unreadable: {e}"
    shards = [n for n in names if n.endswith(".dlck")]
    if not shards:
        return False, "no shard files"
    marker_path = os.path.join(step_dir, _VERIFIED_MARKER)
    try:
        with open(marker_path) as f:
            already_verified = json.load(f).get("files", {})
    except Exception:  # noqa: BLE001 - absent or corrupt cache: re-crc
        already_verified = {}
    newly_verified = {}
    for fname in shards:
        fpath = os.path.join(step_dir, fname)
        mpath = os.path.join(step_dir, fname[: -len(".dlck")] +
                             ".manifest.json")
        header = read_host_shard_meta(fpath)
        if header is None:
            return False, f"{fname}: missing or unreadable shard"
        meta, payload_start = header
        if os.path.exists(mpath):
            try:
                with open(mpath) as f:
                    manifest = json.load(f)
                want_crc = int(manifest["payload_crc"])
                want_nbytes = int(manifest["payload_nbytes"])
            except (OSError, ValueError, KeyError, TypeError) as e:
                return False, f"{fname}: corrupted manifest ({e})"
        else:
            # legacy checkpoint (pre-manifest): the crc embedded in the
            # shard's own meta is the only integrity signal; pre-crc
            # shards (payload_crc < 0) still get the SIZE check below —
            # a torn legacy shard must fail verify, not crash the
            # loader's np.frombuffer
            want_crc = (
                meta.payload_crc if meta.payload_crc >= 0 else None
            )
            want_nbytes = meta.total_bytes
        try:
            actual_nbytes = os.path.getsize(fpath) - payload_start
        except OSError as e:
            return False, f"{fname}: unreadable ({e})"
        if actual_nbytes != want_nbytes:
            return False, (
                f"{fname}: torn payload ({actual_nbytes} bytes, "
                f"expected {want_nbytes})"
            )
        if not deep or want_crc is None:
            continue  # size-verified; no (or loader-side) payload crc
        if already_verified.get(fname) == want_nbytes:
            continue  # full crc already paid by a previous verifier
        try:
            got_crc, got_nbytes = _file_payload_crc(fpath, payload_start)
        except OSError as e:
            return False, f"{fname}: unreadable payload ({e})"
        if got_nbytes != want_nbytes:
            return False, (
                f"{fname}: torn payload ({got_nbytes} bytes, expected "
                f"{want_nbytes})"
            )
        if got_crc != want_crc:
            return False, (
                f"{fname}: checksum mismatch (want {want_crc:08x} got "
                f"{got_crc:08x})"
            )
        newly_verified[fname] = want_nbytes
    if newly_verified:
        # best-effort cache write (atomic rename); read-only storage
        # just means every verifier pays the full crc
        try:
            already_verified.update(newly_verified)
            tmp = marker_path + f".tmp.{os.getpid()}"
            # dlint: allow-chaos(best-effort verify cache: a torn/corrupt marker fails json.load and only costs a re-crc; sizes are cross-checked against the manifest on every read)
            with open(tmp, "w") as f:
                json.dump({"files": already_verified}, f)
            os.replace(tmp, marker_path)
        except OSError:
            pass
    return True, ""


def list_step_numbers(checkpoint_dir: str) -> list[int]:
    """Persisted step-dir numbers under ``checkpoint_dir``, newest
    first. The ONE place that knows the dir-name/.tmp convention — the
    engine's candidate scan and the agent's verified scan both build on
    it, so the consensus report can never skew from what the restore
    path will actually consider."""
    prefix = CheckpointConstant.STEP_DIR_PREFIX
    steps: set[int] = set()
    try:
        for name in os.listdir(checkpoint_dir):
            if not name.startswith(prefix) or name.endswith(".tmp"):
                continue
            try:
                steps.add(int(name[len(prefix):]))
            except ValueError:
                continue
    except OSError:
        pass
    return sorted(steps, reverse=True)


def verified_storage_steps(
    checkpoint_dir: str, limit: int = 64
) -> list[int]:
    """The newest (up to ``limit``) persisted steps whose directories
    pass the DEEP verify (payload CRCs included). This feeds the
    master's restore-step consensus, and the restore path deep-verifies
    its candidates — advertising on a shallower check would let a
    bit-rotted step become the job-wide consensus, fail every restore,
    and livelock the whole job in restart loops. The ``.verified``
    marker caches full-CRC work per step dir, so only the first scan
    after a persist pays the read.

    ``limit`` bounds the scan; it sits far above any sane retention
    policy (keep-latest-N), but a host that somehow retains more dirs
    gets a LOUD log when truncation could hide a cross-host common
    step from the consensus intersection — never a silent cap."""
    prefix = CheckpointConstant.STEP_DIR_PREFIX
    out: list[int] = []
    steps = list_step_numbers(checkpoint_dir)
    for step in steps:
        if len(out) >= limit:
            logger.warning(
                "verified-step scan truncated at %d of %d step dirs "
                "under %s: steps older than %d are not advertised for "
                "restore consensus",
                limit, len(steps), checkpoint_dir, out[-1],
            )
            break
        step_dir = os.path.join(checkpoint_dir, f"{prefix}{step}")
        ok, _reason = verify_step_dir(step_dir, deep=True)
        if ok:
            out.append(step)
    return out


def newest_verified_step(checkpoint_dir: str) -> int:
    steps = verified_storage_steps(checkpoint_dir, limit=1)
    return steps[0] if steps else -1


def read_host_shard_meta(
    path: str,
) -> tuple[CheckpointMeta, int] | None:
    """Read ONLY the pickled meta of a ``.dlck`` host-shard file.

    Returns (meta, payload_start_offset). The payload stays on disk so
    restores can ``np.memmap`` exactly the byte ranges a target shard
    intersects (scalable resharded restore — the full-file read of
    :func:`read_host_shard` materialises every saved byte). Slice reads
    cannot verify the whole-payload CRC without defeating their point;
    the eager path keeps the check.
    """
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            meta_len = int.from_bytes(f.read(_META_LEN_SIZE), "little")
            meta = pickle.loads(f.read(meta_len))
    except Exception:  # noqa: BLE001 - torn header/meta region
        logger.error("unreadable shard meta in %s; rejecting", path)
        return None
    return meta, _META_LEN_SIZE + meta_len


def read_host_shard(
    path: str, stats: dict | None = None
) -> tuple[CheckpointMeta, memoryview] | None:
    """Read one ``.dlck`` shard: chunked ``readinto`` with the CRC
    verified INCREMENTALLY on each chunk as it lands — one traversal,
    transient memory beyond the returned payload stays O(chunk) (the
    old shape ``f.read(total)`` + a second full CRC pass doubled the
    passes and spiked peak RSS on multi-GB shards). Torn headers and
    short payloads are rejected exactly like before.

    Returns (meta, payload) where payload is a READ-ONLY memoryview
    (callers build numpy views over it, as with the old ``bytes``).
    ``stats`` (optional) accumulates ``read_s``/``verify_s``/``bytes``
    for the staged restore breakdown."""
    if not os.path.exists(path):
        return None
    from dlrover_tpu import native as dlrtpu_native

    try:
        with open(path, "rb") as f:
            meta_len = int.from_bytes(f.read(_META_LEN_SIZE), "little")
            meta = pickle.loads(f.read(meta_len))
            # uninitialized allocation: bytearray(n) would memset the
            # whole multi-GB buffer to zero just for readinto to
            # overwrite it — a full extra memory-bandwidth pass
            import numpy as _np

            mv = memoryview(_np.empty(meta.total_bytes, _np.uint8))
            crc = 0
            filled = 0
            check = meta.payload_crc >= 0
            while filled < meta.total_bytes:
                t0 = time.perf_counter()
                got = f.readinto(
                    mv[filled : filled + _READ_CHUNK]
                )
                t1 = time.perf_counter()
                if not got:
                    break
                if check:
                    crc = dlrtpu_native.crc32(
                        mv[filled : filled + got], crc
                    )
                if stats is not None:
                    stats["read_s"] = stats.get("read_s", 0.0) + (t1 - t0)
                    stats["verify_s"] = stats.get("verify_s", 0.0) + (
                        time.perf_counter() - t1
                    )
                filled += got
    except Exception:  # noqa: BLE001 - torn header/meta region
        logger.error("unreadable shard meta in %s; rejecting", path)
        return None
    if filled < meta.total_bytes:
        logger.error(
            "torn payload in %s (%d of %d bytes); rejecting shard",
            path, filled, meta.total_bytes,
        )
        return None
    if check and crc != meta.payload_crc:
        logger.error(
            "checksum mismatch reading %s (want %08x got %08x); "
            "rejecting shard", path, meta.payload_crc, crc,
        )
        return None
    if stats is not None:
        stats["bytes"] = stats.get("bytes", 0) + meta.total_bytes
    return meta, mv.toreadonly()


# --------------------------------------------------------------------------
# the agent-side daemon
# --------------------------------------------------------------------------


class AsyncCheckpointSaver:
    """Agent-side daemon: listens for save events from the training
    process and persists shm checkpoints to storage in the background.

    One instance per host; handles all local ranks' shm segments.
    """

    _saver_instance: "AsyncCheckpointSaver | None" = None
    _factory_thread: threading.Thread | None = None

    def __init__(
        self,
        checkpoint_dir: str = "",
        local_shard_num: int = 1,
        host_rank: int = 0,
        num_hosts: int = 1,
        master_client=None,
        storage=None,
        deletion_strategy=None,
    ):
        self.checkpoint_dir = checkpoint_dir
        self.local_shard_num = local_shard_num
        self.host_rank = host_rank
        self.num_hosts = num_hosts
        self._master_client = master_client
        # Retention (reference KeepStepIntervalStrategy/
        # KeepLatestStepStrategy): applied through the storage's commit
        # hook so non-POSIX backends stay in charge of their own
        # deletion. None = keep everything; env
        # DLROVER_TPU_MAX_CKPTS_TO_KEEP=<n> selects keep-latest-n.
        if deletion_strategy is None and storage is None:
            raw = os.environ.get("DLROVER_TPU_MAX_CKPTS_TO_KEEP", "")
            try:
                keep = int(raw or 0)
            except ValueError:
                logger.warning(
                    "ignoring malformed DLROVER_TPU_MAX_CKPTS_TO_KEEP=%r",
                    raw,
                )
                keep = 0
            if keep > 0 and checkpoint_dir:
                from dlrover_tpu.common.storage import (
                    KeepLatestStepStrategy,
                )

                deletion_strategy = KeepLatestStepStrategy(
                    keep, checkpoint_dir
                )
        if storage is None:
            from dlrover_tpu.common.storage import get_checkpoint_storage

            storage = get_checkpoint_storage(deletion_strategy)
        elif deletion_strategy is not None:
            # attach the caller's policy to their storage when possible;
            # never silently drop an explicit retention request
            if getattr(storage, "_deletion_strategy", "absent") is None:
                storage._deletion_strategy = deletion_strategy
            else:
                logger.warning(
                    "deletion_strategy ignored: the provided storage "
                    "already manages retention"
                )
        self._storage = storage
        self._shm_handlers = [
            SharedMemoryHandler(i) for i in range(local_shard_num)
        ]
        self._shm_locks = [
            SharedLock(lock_name(i), create=True)
            for i in range(local_shard_num)
        ]
        self._event_queues = [
            SharedQueue(event_queue_name(i), create=True)
            for i in range(local_shard_num)
        ]
        # persist-completion wakeups (bounded: a slow/absent consumer
        # must not grow agent memory — stale hints are droppable, the
        # tracker file is the source of truth)
        self._done_queues = [
            SharedQueue(persist_done_queue_name(i), create=True,
                        maxsize=64)
            for i in range(local_shard_num)
        ]
        self._stopped = threading.Event()
        self._threads: list[threading.Thread] = []
        # high-water mark shared by every per-rank saver thread and the
        # SIGTERM flush path: locked max-update, or a lagging rank's
        # commit could roll it backwards past a newer step (dlint
        # DL008). RLock, not Lock: save_shm_to_storage also runs on the
        # MAIN thread (breakpoint flush, SIGTERM handler), so a signal
        # arriving while that same thread holds the lock re-enters the
        # commit path on the interrupted thread — a non-reentrant lock
        # would self-deadlock the dying process exactly like the PR-6
        # logging bug
        self._persist_lock = threading.RLock()
        self._last_persisted_step = -1

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        for i in range(self.local_shard_num):
            t = threading.Thread(
                target=self._sync_shm_to_storage,
                args=(i,),
                name=f"ckpt-saver-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        logger.info(
            "AsyncCheckpointSaver started: dir=%s shards=%d",
            self.checkpoint_dir,
            self.local_shard_num,
        )

    def stop(self, join_timeout: float = 10.0):
        self._stopped.set()
        # wake event threads blocked in q.get so the join is immediate,
        # then bound-join: callers may delete the checkpoint dir right
        # after stop(), and an in-flight persist must not recreate it.
        for q in self._event_queues:
            try:
                q.put(None)
            except Exception:  # noqa: BLE001
                pass
        deadline = time.time() + join_timeout
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(max(0.0, deadline - time.time()))
                if t.is_alive():
                    logger.warning(
                        "saver thread %s still persisting after stop(); "
                        "checkpoint dir must not be deleted yet", t.name
                    )

    @classmethod
    def register_signal_handlers(cls):
        """Persist whatever is in shm before dying on SIGTERM (pod
        eviction) — reference ckpt_saver.py:468."""

        def handler(signum, frame):  # noqa: ARG001
            saver = cls._saver_instance
            # no logging from signal context (dlint DL004, the PR-6
            # bug shape): the handler may have interrupted the main
            # thread while it holds the logging module's non-reentrant
            # handler lock — write to the raw fd instead
            if saver is not None:
                # stderr may be a pipe to an already-dead parent (the
                # very teardown this handler serves): a raised EPIPE
                # here must not abort the flush or the 143 exit
                try:
                    os.write(
                        2,
                        b"SIGTERM: flushing shm checkpoint to storage\n",
                    )
                except OSError:
                    pass
                try:
                    # eviction-time best-effort flush: its locks are
                    # saver-thread-owned, never main-thread, so they
                    # can block here but not self-deadlock
                    saver.save_shm_to_storage()
                except Exception:  # noqa: BLE001
                    try:
                        os.write(2, b"SIGTERM shm flush failed\n")
                    except OSError:
                        pass
            raise SystemExit(143)

        signal.signal(signal.SIGTERM, handler)

    @classmethod
    def start_async_saving_ckpt(cls):
        """Start the factory listener: the training process announces its
        saver config on the factory queue; the agent builds the saver
        (reference ckpt_saver.py:406-461)."""
        if cls._factory_thread is not None:
            return
        factory_queue = SharedQueue(SAVER_FACTORY_QUEUE, create=True)
        cls._factory_queue = factory_queue
        stop = threading.Event()
        cls._factory_stop = stop

        def factory_loop():
            while not stop.is_set():
                try:
                    config = factory_queue.get(timeout=60)
                except _queue.Empty:
                    continue
                except Exception:  # noqa: BLE001
                    if stop.is_set():
                        return
                    time.sleep(1)
                    continue
                try:
                    if cls._saver_instance is None:
                        cls._saver_instance = AsyncCheckpointSaver(**config)
                        cls._saver_instance.start()
                except Exception:  # noqa: BLE001
                    logger.exception("failed to build checkpoint saver")

        cls._factory_thread = threading.Thread(
            target=factory_loop, name="ckpt-saver-factory", daemon=True
        )
        cls._factory_thread.start()

    @classmethod
    def get_ckpt_saver(cls):
        return cls._saver_instance

    @classmethod
    def reset(cls):
        if cls._saver_instance is not None:
            cls._saver_instance.stop()
            cls._saver_instance = None
        # also retire the factory listener: a stale thread bound to a
        # previous socket dir would make the next start_async_saving_ckpt
        # a silent no-op (its queue socket no longer matches the env)
        if cls._factory_thread is not None:
            stop = getattr(cls, "_factory_stop", None)
            if stop is not None:
                stop.set()
            queue_obj = getattr(cls, "_factory_queue", None)
            if queue_obj is not None:
                try:
                    queue_obj.unlink()
                except Exception:  # noqa: BLE001
                    pass
            cls._factory_thread = None
            cls._factory_queue = None
            cls._factory_stop = None

    # -- event loop --------------------------------------------------------

    def _sync_shm_to_storage(self, local_rank: int):
        """Reference ckpt_saver.py:506 — wait for save events, persist."""
        q = self._event_queues[local_rank]
        while not self._stopped.is_set():
            try:
                event: SaveEvent = q.get(timeout=5)
            except _queue.Empty:
                continue
            except Exception:  # noqa: BLE001
                time.sleep(1)
                continue
            if event is None:
                continue  # stop() wake-up sentinel
            if event.storage_type == "memory":
                continue  # shm-only checkpoint; nothing to persist
            try:
                self.save_step_checkpoint(event, local_rank)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "persist step %s failed (rank %d)",
                    event.step,
                    local_rank,
                )

    # -- persistence -------------------------------------------------------

    def _step_dir(self, path: str, step: int) -> str:
        if path:
            return path
        return os.path.join(
            self.checkpoint_dir,
            f"{CheckpointConstant.STEP_DIR_PREFIX}{step}",
        )

    def save_step_checkpoint(self, event: SaveEvent, local_rank: int):
        """Persist one local shard, then run the commit protocol."""
        start = time.time()
        lock = self._shm_locks[local_rank]
        acquired = self._acquire_or_take_over(lock)
        if not acquired:
            # never read shm unlocked: a live writer may be mid-copy and
            # we would persist (and advertise) a torn checkpoint
            logger.error(
                "skipping persist of step %s shard %d: shm lock unavailable",
                event.step,
                local_rank,
            )
            return
        try:
            self._shm_handlers[local_rank].refresh()
            result = self._shm_handlers[local_rank].read()
            if result is None:
                logger.warning("no checkpoint in shm for rank %d", local_rank)
                return
            meta, data = result
            if meta.step != event.step:
                logger.warning(
                    "shm holds step %s, event asked %s; saving shm step",
                    meta.step,
                    event.step,
                )
            step_dir = self._step_dir(event.path, meta.step)
            self._save_shard(step_dir, meta, data, local_rank)
            self._commit_checkpoint(
                step_dir, meta.step, local_rank, engine=meta.engine
            )
        finally:
            if acquired:
                lock.release(force=True)
        # wake any engine blocked in wait_for_persist / the trainer's
        # final-save retry loop: best-effort, non-blocking (a full queue
        # just means the waiter is behind on hints; the tracker file
        # still carries the truth)
        try:
            self._done_queues[local_rank].put(meta.step, block=False)
        except Exception:  # noqa: BLE001 - hint only
            pass
        elapsed = time.time() - start
        # timeline only: the daemon's persist overlaps training, so the
        # goodput ledger deliberately does NOT treat it as lost time
        telemetry.event(
            "ckpt.persist", step=event.step, dur=elapsed,
            shard=local_rank,
        )
        telemetry.observe("ckpt.persist.seconds", elapsed)
        logger.info(
            "persisted step %s shard %d in %.2fs",
            event.step,
            local_rank,
            elapsed,
        )

    def _acquire_or_take_over(
        self, lock, dead_grace: float = 2.0
    ) -> bool:
        """Bounded acquire with forced takeover ONLY from a dead holder.

        A worker that died while holding the shm lock must not deadlock
        the agent's breakpoint flush (the exact crash Flash Checkpoint
        exists to survive) — but a *live* writer mid-copy may legitimately
        hold the lock for a long time (multi-GB D2H), so we never steal
        from a holder whose pid is still alive."""
        deadline = time.time() + CheckpointConstant.SAVE_TIMEOUT
        dead_since = None
        while time.time() < deadline:
            if lock.acquire(blocking=False):
                return True
            owner = lock.owner()
            if owner is not None and _pid_alive(owner):
                dead_since = None  # live writer: wait, never steal
            elif dead_since is None:
                dead_since = time.time()
            elif time.time() - dead_since >= dead_grace:
                logger.warning(
                    "shm lock holder (pid %s) is gone; taking the lock over",
                    owner,
                )
                lock.release(force=True)
                if lock.acquire(blocking=False):
                    return True
                dead_since = None  # lost the race; re-observe
            time.sleep(0.2)
        logger.error(
            "could not acquire shm lock within %.0fs (holder alive)",
            CheckpointConstant.SAVE_TIMEOUT,
        )
        return False

    def _save_shard(self, step_dir, meta, data, local_rank):
        shard_id = self.host_rank * self.local_shard_num + local_rank
        path = os.path.join(step_dir, host_shard_filename(shard_id))
        crc, payload_nbytes = write_host_shard(
            self._storage, path, meta, data
        )
        # manifest lands before the .done marker, the atomic rename and
        # the tracker update: nothing can advertise this shard until its
        # integrity record exists
        write_shard_manifest(
            self._storage, step_dir, shard_id, meta.step,
            crc, payload_nbytes, meta.engine,
        )

    def _commit_checkpoint(
        self, step_dir: str, step: int, local_rank, engine: str = "sharded"
    ):
        """.done marker per shard; when all expected shards are done,
        update the tracker file (reference commit_checkpoint :847)."""
        done_dir = os.path.join(step_dir, ".done")
        self._storage.safe_makedirs(done_dir)
        shard_id = self.host_rank * self.local_shard_num + local_rank
        self._storage.write("", os.path.join(done_dir, f"{shard_id}.done"))
        # replicated engines write from host 0 only; sharded engines from
        # every host
        if engine == "replicated":
            total_shards = self.local_shard_num
        else:
            total_shards = self.local_shard_num * self.num_hosts
        deadline = time.time() + CheckpointConstant.SAVE_TIMEOUT
        while time.time() < deadline:
            done = len(
                [
                    f
                    for f in self._storage.listdir(done_dir)
                    if f.endswith(".done")
                ]
            )
            if done >= total_shards:
                break
            time.sleep(0.5)
        else:
            logger.warning("commit timeout for step %s", step)
            return
        if self._master_client is not None and self.num_hosts > 1:
            # cross-host agreement through the master
            deadline = time.time() + CheckpointConstant.SAVE_TIMEOUT
            while time.time() < deadline:
                if self._master_client.sync_checkpoint(step):
                    break
                time.sleep(0.5)
        # Finalize the directory BEFORE advertising the step in the
        # tracker — a reader must never see a tracker pointing at a dir
        # that does not exist yet.
        self._finalize_step_dir(step_dir)
        if self.host_rank == 0:
            # the tracker must live NEXT TO the step dir it advertises —
            # a custom event.path outside checkpoint_dir gets its own
            # tracker there, not one in checkpoint_dir pointing nowhere
            self._storage.write(
                str(step),
                os.path.join(
                    os.path.dirname(step_dir),
                    CheckpointConstant.TRACKER_FILE,
                ),
            )
            # retention must only run for steps committed under
            # checkpoint_dir: a custom event.path outside it would
            # otherwise evict the tracker's target dir
            if os.path.dirname(step_dir) == self.checkpoint_dir.rstrip(
                "/"
            ):
                self._storage.commit(step, True)
        with self._persist_lock:
            self._last_persisted_step = max(
                self._last_persisted_step, step
            )

    def _finalize_step_dir(self, step_dir: str):
        """Hook for atomic-rename savers; base saver writes in place."""

    def save_shm_to_storage(self):
        """Flush every local shard currently in shm to storage — called
        when a worker dies or the agent gets SIGTERM (reference :622)."""
        for local_rank in range(self.local_shard_num):
            self._shm_handlers[local_rank].refresh()
            result = self._shm_handlers[local_rank].read()
            if result is None:
                continue
            meta, _ = result
            # locked read: this runs on the main/SIGTERM thread while
            # saver threads still commit; the lock is reentrant, so a
            # handler interrupting this very thread mid-hold re-enters
            # instead of self-deadlocking
            with self._persist_lock:
                last_persisted = self._last_persisted_step
            if meta.step <= last_persisted:
                continue
            event = SaveEvent(step=meta.step, storage_type="disk")
            try:
                self.save_step_checkpoint(event, local_rank)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "breakpoint flush of shard %d failed", local_rank
                )

    # -- queries -----------------------------------------------------------

    @staticmethod
    def get_latest_step(checkpoint_dir: str) -> int:
        tracker = os.path.join(
            checkpoint_dir, CheckpointConstant.TRACKER_FILE
        )
        if not os.path.exists(tracker):
            return -1
        try:
            with open(tracker) as f:
                return int(f.read().strip())
        except (ValueError, OSError):
            return -1


class TempDirCheckpointSaver(AsyncCheckpointSaver):
    """Writes into a temp dir then atomically renames into place
    (reference TempDirCheckpointSaver :908). The rename happens in
    _finalize_step_dir, i.e. strictly before the tracker update."""

    def _step_dir(self, path: str, step: int) -> str:
        final = super()._step_dir(path, step)
        return final + ".tmp"

    def _finalize_step_dir(self, step_dir: str):
        if self.host_rank == 0 and step_dir.endswith(".tmp"):
            final = step_dir[: -len(".tmp")]
            if os.path.exists(step_dir) and not os.path.exists(final):
                os.replace(step_dir, final)
