"""Node health-check payload: device matmul + collective probe.

Equivalent capability: reference dlrover/trainer/torch/node_check/
nvidia_gpu.py:26 (matmul rounds + 10x allgather of 2^24 floats, elapsed
time written to a per-rank file; MOCK_ERR_RANK fault injection
utils.py:50). TPU-native redesign: the probe runs a bf16 matmul loop on
every local TPU device (MXU exercise) and a psum+all_gather over all
local devices via pmap (ICI exercise); multi-host probes run the same
program under jax.distributed so the collectives cross hosts. The agent
times the run and reports (normal, elapsed) to the master, whose pairing
logic (master/rendezvous.py NetworkCheckRendezvousManager) isolates the
faulty node.
"""

from __future__ import annotations

import json
import os
import time

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

CHECK_TIME_DIR = "/tmp/dlrover_tpu/node_check"

MATMUL_SIZE = 1024
MATMUL_ROUNDS = 10
COLLECTIVE_ELEMS = 1 << 22  # 4M floats ~= 16MB, all_gather x devices
COLLECTIVE_ROUNDS = 10


def _mock_error() -> bool:
    """Fault injection: MOCK_ERR_RANK=<node_rank> makes that node fail."""
    mock_rank = os.environ.get(NodeEnv.MOCK_ERR_RANK, "")
    node_rank = os.environ.get(NodeEnv.NODE_RANK, "0")
    return mock_rank != "" and mock_rank == node_rank


def matmul_probe(devices=None) -> float:
    """Time a bf16 matmul loop on each local device (MXU health)."""
    import jax
    import jax.numpy as jnp

    devices = devices or jax.local_devices()
    start = time.time()
    for dev in devices:
        x = jax.device_put(
            jnp.ones((MATMUL_SIZE, MATMUL_SIZE), dtype=jnp.bfloat16), dev
        )
        for _ in range(MATMUL_ROUNDS):
            x = jnp.matmul(x, x) / MATMUL_SIZE
        x.block_until_ready()
    return time.time() - start


def collective_probe(devices=None) -> float:
    """Time psum + all_gather across local devices (ICI health); with a
    multi-process jax.distributed setup the same collectives span DCN."""
    import jax
    import jax.numpy as jnp

    devices = devices or jax.local_devices()
    n = len(devices)
    if n == 0:
        raise RuntimeError("no devices to probe")
    shape = (n, COLLECTIVE_ELEMS // max(n, 1))
    x = jnp.ones(shape, dtype=jnp.float32)

    probe = jax.pmap(
        lambda v: jax.lax.psum(v, axis_name="d"),
        axis_name="d",
        devices=devices,
    )
    start = time.time()
    for _ in range(COLLECTIVE_ROUNDS):
        out = probe(x)
    out.block_until_ready()
    return time.time() - start


def write_time_to_file(elapsed: float, normal: bool, local_rank: int = 0):
    os.makedirs(CHECK_TIME_DIR, exist_ok=True)
    path = os.path.join(CHECK_TIME_DIR, f"{local_rank}.json")
    with open(path, "w") as f:
        json.dump(
            {"elapsed": elapsed, "normal": normal, "ts": time.time()}, f
        )


def read_time_from_file(local_rank: int = 0):
    path = os.path.join(CHECK_TIME_DIR, f"{local_rank}.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def run_node_check(local_rank: int = 0) -> tuple[bool, float]:
    """The payload the agent executes (in-process or as a subprocess).

    Returns (normal, elapsed_seconds)."""
    start = time.time()
    normal = True
    try:
        if _mock_error():
            raise RuntimeError("mock node failure injected via MOCK_ERR_RANK")
        import jax

        devices = jax.local_devices()
        if not devices:
            raise RuntimeError("no local devices enumerated")
        matmul_probe(devices)
        collective_probe(devices)
    except Exception as e:  # noqa: BLE001
        logger.error("node check failed: %s", e)
        normal = False
    elapsed = time.time() - start
    write_time_to_file(elapsed, normal, local_rank)
    return normal, elapsed


def main():
    normal, elapsed = run_node_check(
        int(os.environ.get(NodeEnv.LOCAL_RANK, "0"))
    )
    logger.info("node check: normal=%s elapsed=%.2fs", normal, elapsed)
    raise SystemExit(0 if normal else 1)


if __name__ == "__main__":
    main()
