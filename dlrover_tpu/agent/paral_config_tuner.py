"""ParalConfigTuner: agent-side runtime-tunable parallel config.

Equivalent capability: reference dlrover/python/elastic_agent/config/
paral_config_tuner.py:30 — polls the master every ``interval`` seconds for
the node's ``ParallelConfig`` and writes it as JSON to the path the trainer
watches (``DLROVER_PARAL_CONFIG_PATH``), so dataloader batch size /
optimizer hyperparams hot-update without a restart
(:class:`~dlrover_tpu.trainer.elastic.ElasticDataLoader` reads this file).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading

from dlrover_tpu.common.constants import ConfigPath
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.retry import NonCriticalGuard
from dlrover_tpu.agent.master_client import MasterClient

logger = get_logger(__name__)


class ParalConfigTuner:
    def __init__(self, client: MasterClient | None = None,
                 config_path: str | None = None,
                 interval: float = 30.0):
        self._client = client or MasterClient.singleton_instance()
        self._config_path = config_path or os.getenv(
            ConfigPath.ENV_PARAL_CONFIG, ConfigPath.PARAL_CONFIG
        )
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_written: str = ""
        # tuning is best-effort: exhausted retry budgets degrade the
        # tuner to off (the trainer keeps its last config) rather than
        # hammering a dead master forever
        self._guard = NonCriticalGuard("paral-config-tuner")
        # export the path so worker processes spawned later inherit it
        os.environ[ConfigPath.ENV_PARAL_CONFIG] = self._config_path

    @property
    def degraded(self) -> bool:
        return self._guard.disabled

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="paral-config-tuner", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def _run(self):
        while not self._stopped.is_set():
            try:
                self.tune_once()
            except Exception:  # noqa: BLE001
                logger.exception("paral-config poll failed")
            if self._guard.disabled:
                logger.warning(
                    "paral-config tuner degraded; stopping the poll loop"
                )
                return
            self._stopped.wait(self._interval)

    def tune_once(self) -> bool:
        """One poll+write cycle; returns True if the file was (re)written."""
        if self._client is None:
            return False
        config = self._guard.run(self._client.get_paral_config)
        if config is None:
            return False
        payload = json.dumps(dataclasses.asdict(config), sort_keys=True)
        if payload == self._last_written:
            return False
        config_dir = os.path.dirname(self._config_path)
        if config_dir:
            os.makedirs(config_dir, exist_ok=True)
        tmp = self._config_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, self._config_path)
        self._last_written = payload
        logger.info("paral config updated: %s", payload[:200])
        return True
