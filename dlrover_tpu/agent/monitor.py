"""Agent-side monitors: node resources + training heartbeats.

Equivalent capability: reference dlrover/python/elastic_agent/monitor/
resource.py:86 (ResourceMonitor: psutil + accelerator stats ->
report_used_resource) and monitor/training.py:77 (TorchTrainingMonitor:
heartbeats + per-step metrics file).
"""

from __future__ import annotations

import json
import os
import threading
import time

from dlrover_tpu.common.constants import ConfigPath, JobConstant
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.retry import NonCriticalGuard

logger = get_logger(__name__)


def get_process_cpu_percent() -> float:
    try:
        import psutil

        return psutil.cpu_percent(interval=None)
    except Exception:  # noqa: BLE001
        return 0.0


def get_used_memory_mb() -> int:
    try:
        import psutil

        return int(psutil.virtual_memory().used / (1024 * 1024))
    except Exception:  # noqa: BLE001
        return 0


def get_tpu_stats() -> list:
    """Best-effort TPU device stats via jax; empty off-device."""
    try:
        import jax

        stats = []
        for i, dev in enumerate(jax.local_devices()):
            mem = getattr(dev, "memory_stats", None)
            entry = {"index": i}
            if callable(mem):
                m = mem() or {}
                entry["memory_used_gb"] = m.get("bytes_in_use", 0) / 1e9
                entry["memory_total_gb"] = m.get("bytes_limit", 0) / 1e9
            stats.append(entry)
        return stats
    except Exception:  # noqa: BLE001
        return []


class ResourceMonitor:
    """Periodically reports host CPU/mem (+ TPU stats) to the master."""

    # Stats are best-effort, but a healed partition must bring them
    # back: the guard is a circuit breaker (many misses to trip, then
    # periodic half-open probes), never a permanent off-switch —
    # permanently silent step/resource reports could later be misread
    # by the master as a job-wide hang.
    _MAX_MISSES = 20
    _COOLDOWN = 300.0

    def __init__(self, master_client, interval=JobConstant.MONITOR_INTERVAL):
        self._client = master_client
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        self._guard = NonCriticalGuard(
            "resource-monitor",
            max_consecutive_failures=self._MAX_MISSES,
            cooldown=self._COOLDOWN,
        )
        self.report_tpu = False

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="resource-monitor", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def _loop(self):
        while not self._stopped.is_set():
            try:
                self._guard.run(
                    lambda: self._client.report_used_resource(
                        get_process_cpu_percent(),
                        get_used_memory_mb(),
                        get_tpu_stats() if self.report_tpu else [],
                    )
                )
            except Exception:  # noqa: BLE001
                pass
            self._stopped.wait(self._interval)


class HeartbeatReporter:
    """Agent heartbeat loop; the master's heartbeat-timeout monitor
    declares the node dead if these stop arriving.

    Tracks consecutive transport-level misses so the agent can tell a
    dead/restarting MASTER (every heartbeat's whole retry budget
    exhausted) from a transient blip, and enter its ride-through path
    instead of letting workers discover the outage one RPC at a time."""

    # misses before ``master_unreachable`` flips: each miss already
    # burned a full RetryPolicy budget, so 2 in a row is a real outage
    UNREACHABLE_MISSES = 2

    def __init__(self, master_client, interval=JobConstant.MONITOR_INTERVAL):
        self._client = master_client
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        self.action = ""
        self.misses = 0

    @property
    def master_unreachable(self) -> bool:
        return self.misses >= self.UNREACHABLE_MISSES

    def reset_misses(self):
        self.misses = 0

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="heartbeat", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def _loop(self):
        while not self._stopped.is_set():
            try:
                resp = self._client.report_heart_beat()
                self.misses = 0
                if resp.action:
                    self.action = resp.action
            except (ConnectionError, OSError):
                self.misses += 1
            except Exception:  # noqa: BLE001
                pass
            self._stopped.wait(self._interval)


class TrainingMetricsReporter:
    """Relays per-step metrics a worker writes to the runtime-metrics
    file up to the master (global step -> speed monitor)."""

    # circuit breaker, not a kill switch: see ResourceMonitor
    _MAX_MISSES = 20
    _COOLDOWN = 300.0

    def __init__(self, master_client, interval=JobConstant.MONITOR_INTERVAL):
        self._client = master_client
        self._interval = interval
        self._stopped = threading.Event()
        self._last_step = -1
        self._guard = NonCriticalGuard(
            "metrics-reporter",
            max_consecutive_failures=self._MAX_MISSES,
            cooldown=self._COOLDOWN,
        )
        self._path = os.environ.get(
            ConfigPath.ENV_RUNTIME_METRICS, ConfigPath.RUNTIME_METRICS
        )

    def start(self):
        threading.Thread(
            target=self._loop, name="metrics-reporter", daemon=True
        ).start()

    def stop(self):
        self._stopped.set()

    def _report_once(self):
        if not os.path.exists(self._path):
            return
        with open(self._path) as f:
            metrics = json.load(f)
        step = int(metrics.get("step", -1))
        if step > self._last_step:
            self._client.report_global_step(
                step, metrics.get("timestamp", time.time())
            )
            self._last_step = step

    def _loop(self):
        while not self._stopped.is_set():
            try:
                self._guard.run(self._report_once)
            except Exception:  # noqa: BLE001
                pass
            self._stopped.wait(self._interval)


class TelemetryReporter:
    """Ships telemetry snapshots to the master on a cadence: this
    process's own registry, plus any snapshot files other processes of
    this host (workers) flushed into ``DLROVER_TELEMETRY_DIR`` — the
    workers have no control-plane client, so the agent is their relay.
    Each tick also re-flushes the local snapshot so the on-disk copy
    used by ``tools/obs_report.py --dir`` stays fresh.

    Shipping is DELTA-ENCODED: after a source's full snapshot was acked
    once, later ticks send only what changed since that ack
    (``telemetry.snapshot_delta``) — the wire and master-merge cost
    scale with activity, not registry size. A rejected delta (master
    failover lost our base, re-registration) drops the cursor so the
    next tick re-sends the full snapshot; an unchanged registry sends
    nothing at all.

    Best-effort like the other stats reporters: a NonCriticalGuard
    circuit breaker, never a training stall."""

    # circuit breaker, not a kill switch: see ResourceMonitor
    _MAX_MISSES = 20
    _COOLDOWN = 300.0

    def __init__(self, master_client, interval=JobConstant.MONITOR_INTERVAL):
        self._client = master_client
        self._interval = interval
        self._stopped = threading.Event()
        self._guard = NonCriticalGuard(
            "telemetry-reporter",
            max_consecutive_failures=self._MAX_MISSES,
            cooldown=self._COOLDOWN,
        )
        # source -> last shipped (mtime, size): only changed files go out
        self._shipped: dict = {}
        # source -> last ACKED full snapshot (the delta base). Bounded
        # by what this host itself produces (own registry + its
        # workers' snapshot files).
        self._acked: dict = {}

    def reset_shipped(self):
        """Forget what was shipped — after a master failover the new
        incarnation's merge may predate snapshots this host already
        sent, so re-send everything (FULL, not deltas against a base
        the new master never saw) on the next tick."""
        self._shipped = {}
        self._acked = {}

    def start(self):
        threading.Thread(
            target=self._loop, name="telemetry-reporter", daemon=True
        ).start()

    def stop(self):
        self._stopped.set()

    def _ship(self, snap: dict) -> bool:
        """Send one source's cumulative snapshot, delta-encoded when a
        base was acked. Returns True when the master accepted it (the
        acked base advances); a rejected/failed delta clears the base
        so the next attempt is a full re-send."""
        from dlrover_tpu.common import telemetry

        source = snap.get("source")
        base = self._acked.get(source)
        payload = snap
        if base is not None:
            payload = telemetry.snapshot_delta(base, snap)
            if not (
                payload["counters"] or payload["gauges"]
                or payload["histograms"] or payload["series"]
                or payload["events"]
            ):
                return True  # nothing changed: keep the old base
        ok = self._guard.run(
            lambda: self._client.report_telemetry(payload)
        )
        if ok:
            self._acked[source] = snap
        elif base is not None:
            self._acked.pop(source, None)
        return bool(ok)

    def report_once(self, swallow: bool = False):
        from dlrover_tpu.common import telemetry

        try:
            telemetry.flush()
            snap = telemetry.snapshot()
            if snap is not None:
                self._ship(snap)
            own = snap["source"] if snap else None
            for path, source in self._snapshot_files(own):
                try:
                    stat = os.stat(path)
                    stamp = (stat.st_mtime, stat.st_size)
                    if self._shipped.get(source) == stamp:
                        continue
                    with open(path) as f:
                        payload = json.load(f)
                except (OSError, ValueError):
                    continue  # torn write / vanished file: next tick
                if self._ship(payload):
                    self._shipped[source] = stamp
        except Exception:  # noqa: BLE001 - relaying telemetry must
            # never take the agent down — but a silently dead reporter
            # would contradict this layer's whole purpose, so say so
            logger.warning(
                "telemetry report tick failed", exc_info=True
            )
            if not swallow:
                raise

    @staticmethod
    def _snapshot_files(own_source):
        from dlrover_tpu.common import telemetry

        out_dir = os.environ.get(telemetry.ENV_DIR, "")
        if not out_dir:
            return
        for path, source in telemetry.snapshot_files(out_dir):
            if own_source is not None and source == own_source:
                continue  # already shipped straight from memory
            yield path, source

    def _loop(self):
        while not self._stopped.is_set():
            self.report_once(swallow=True)
            self._stopped.wait(self._interval)


class TimerRingExporter:
    """Drains the shared timing ring and exports per-tag aggregates —
    the out-of-process half of the xpu_timer capability (reference
    atorch/dev/xpu_timer: in-proc hook -> shm -> brpc/Prometheus
    exporter; here: StepTimer -> shm ring -> JSON file + logs)."""

    def __init__(self, interval=JobConstant.MONITOR_INTERVAL,
                 out_path: str | None = None):
        self._interval = interval
        self._stopped = threading.Event()
        self._out_path = out_path or os.path.join(
            os.path.dirname(ConfigPath.RUNTIME_METRICS),
            "timer_stats.json",
        )
        self._timer = None
        self._totals: dict = {}
        self._export_lock = threading.Lock()

    def start(self):
        threading.Thread(
            target=self._loop, name="timer-exporter", daemon=True
        ).start()

    def stop(self):
        self._stopped.set()

    def _ensure_timer(self):
        if self._timer is None:
            from dlrover_tpu.trainer.timer import get_step_timer

            self._timer = get_step_timer()
        return self._timer

    def export_once(self) -> dict:
        """Drain + aggregate; returns {tag_name: {count, avg_ms, max_ms}}.
        Thread-safe: the /metrics endpoint and the export loop may both
        call this."""
        with self._export_lock:
            return self._export_once_locked()

    def _export_once_locked(self) -> dict:
        from dlrover_tpu.common import telemetry
        from dlrover_tpu.trainer.timer import Tag

        try:
            records = self._ensure_timer().drain()
        except Exception:  # noqa: BLE001 - ring not created yet
            return {}
        recent: dict = {}
        for tag, _start, dur in records:
            agg = self._totals.setdefault(
                tag, {"count": 0, "total_ns": 0, "max_ns": 0}
            )
            agg["count"] += 1
            agg["total_ns"] += dur
            agg["max_ns"] = max(agg["max_ns"], dur)
            r = recent.setdefault(tag, {"count": 0, "total_ns": 0})
            r["count"] += 1
            r["total_ns"] += dur
        stats = {
            Tag.NAMES.get(tag, str(tag)): {
                "count": a["count"],
                "avg_ms": round(a["total_ns"] / a["count"] / 1e6, 3),
                "max_ms": round(a["max_ns"] / 1e6, 3),
            }
            for tag, a in self._totals.items()
        }
        # publish the aggregates into this agent's telemetry registry:
        # the TelemetryReporter relays them to the master, where
        # master/diagnosis.py z-scores them ACROSS hosts — the
        # out-of-process half of the xpu_timer capability becomes a
        # fleet-wide straggler signal, not just a local JSON file.
        # recent_avg = the window drained THIS tick, so a host that
        # becomes slow shows up immediately instead of diluting into
        # its lifetime average.
        for name, agg in stats.items():
            telemetry.gauge_set(
                "timer.phase.avg_ms", agg["avg_ms"], phase=name
            )
            telemetry.gauge_set(
                "timer.phase.max_ms", agg["max_ms"], phase=name
            )
            telemetry.gauge_set(
                "timer.phase.count", agg["count"], phase=name
            )
        for tag, r in recent.items():
            telemetry.gauge_set(
                "timer.phase.recent_avg_ms",
                round(r["total_ns"] / r["count"] / 1e6, 3),
                phase=Tag.NAMES.get(tag, str(tag)),
            )
        if records:
            os.makedirs(os.path.dirname(self._out_path), exist_ok=True)
            tmp = f"{self._out_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(stats, f)
            os.replace(tmp, self._out_path)
        return stats

    def _loop(self):
        while not self._stopped.is_set():
            try:
                self.export_once()
            except Exception:  # noqa: BLE001
                pass
            self._stopped.wait(self._interval)


class MetricsEndpoint:
    """HTTP ``/metrics`` in Prometheus text exposition format.

    Equivalent capability: reference xpu_timer's brpc/Prometheus export
    (atorch/dev/xpu_timer/xpu_timer/common/manager.cc) — something a
    cluster monitoring stack can actually scrape, instead of (only) the
    JSON file the TimerRingExporter writes. Serves the timer aggregates
    plus the worker's latest global step and host resource gauges."""

    def __init__(self, exporter: TimerRingExporter | None = None,
                 host: str = "0.0.0.0", port: int = 0):
        self._exporter = exporter
        self._host = host
        self._port = port
        self._server = None
        self.port = 0  # actual bound port after start()

    # ------------------------------------------------------------ render

    def render(self) -> str:
        lines = []

        def metric(name, help_, mtype, samples):
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {mtype}")
            for labels, value in samples:
                label_s = (
                    "{" + ",".join(
                        f'{k}="{v}"' for k, v in labels.items()
                    ) + "}" if labels else ""
                )
                lines.append(f"{name}{label_s} {value}")

        stats = self._exporter.export_once() if self._exporter else {}
        if stats:
            metric(
                "dlrtpu_timer_events_total",
                "Timed events per tag (from the shm timing ring)",
                "counter",
                [({"tag": t}, a["count"]) for t, a in stats.items()],
            )
            metric(
                "dlrtpu_timer_avg_ms",
                "Average duration per tag in milliseconds",
                "gauge",
                [({"tag": t}, a["avg_ms"]) for t, a in stats.items()],
            )
            metric(
                "dlrtpu_timer_max_ms",
                "Max duration per tag in milliseconds",
                "gauge",
                [({"tag": t}, a["max_ms"]) for t, a in stats.items()],
            )
        path = os.environ.get(
            ConfigPath.ENV_RUNTIME_METRICS, ConfigPath.RUNTIME_METRICS
        )
        try:
            with open(path) as f:
                rt = json.load(f)
            metric(
                "dlrtpu_global_step", "Latest reported training step",
                "gauge", [({}, int(rt.get("step", 0)))],
            )
        except Exception:  # noqa: BLE001 - no worker progress yet
            pass
        kpath = os.environ.get(
            ConfigPath.ENV_KERNEL_METRICS, ConfigPath.KERNEL_METRICS
        )
        try:
            with open(kpath) as f:
                kern = json.load(f)
            ops = kern.get("top_ops") or []
            if ops:
                # per-op self time from the latest XPlane step window
                # (trainer/profiler.py publish_kernel_stats) — the
                # online xpu_timer-style named-kernel export
                metric(
                    "dlrtpu_kernel_self_ms",
                    "Top HLO ops by self time per step (XPlane window)",
                    "gauge",
                    [
                        ({"op": o["op"], "category": o["category"]},
                         o["self_ms_per_step"])
                        for o in ops
                    ],
                )
        except Exception:  # noqa: BLE001 - no profiled window yet
            pass
        metric(
            "dlrtpu_host_memory_used_mb", "Host memory in use",
            "gauge", [({}, get_used_memory_mb())],
        )
        return "\n".join(lines) + "\n"

    # ------------------------------------------------------------- serve

    def start(self) -> int:
        import http.server

        endpoint = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API
                path = self.path.split("?", 1)[0].rstrip("/")
                if path not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = endpoint.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet
                pass

        self._server = http.server.ThreadingHTTPServer(
            (self._host, self._port), Handler
        )
        self.port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, name="metrics-http",
            daemon=True,
        ).start()
        logger.info("/metrics endpoint on port %d", self.port)
        return self.port

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def write_runtime_metrics(step: int, **extra):
    """Called from the training loop (worker side) to publish progress."""
    path = os.environ.get(
        ConfigPath.ENV_RUNTIME_METRICS, ConfigPath.RUNTIME_METRICS
    )
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"step": step, "timestamp": time.time(), **extra}, f)
    os.replace(tmp, path)
