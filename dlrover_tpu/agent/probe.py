"""Join-time hardware health probe: per-leg timings shipped with the join.

Equivalent capability: the reference admits a node only after a
``NetworkCheckElasticAgent`` runs a matmul + repeated-allgather payload
and kills hosts that fail it (node_check/nvidia_gpu.py); our
node_check.py reproduces the pass/fail half for dedicated probe rounds.
This module is the *graded* half: three timed legs run by the agent
BEFORE ``rdzv.join``, with per-leg milliseconds shipped in
``JoinRendezvousRequest.probe_report`` so the master's health gate
(master/health.py) can judge the host against the fleet median AND its
own persisted fingerprint — pass / quarantine / refuse instead of the
binary normal flag.

Legs (TPU; CPU smoke-arm stand-ins in parentheses):

- ``hbm``        — HBM-bandwidth microbench: on-device array copy
                   rounds (host memcpy over a scaled buffer).
- ``matmul``     — an MXU matmul round per local device (numpy matmul
                   — a jitted jax matmul on CPU would time XLA
                   compilation, not the hardware).
- ``collective`` — N ICI psum rounds over the local mesh via pmap
                   (loopback-socket round trips: the only in-process
                   stand-in that still exercises a real network stack).

Every leg opens its timed window with ``chaos_point("probe.degrade",
leg=..., rank=...)`` — the ``degrade`` action (common/chaos.py) injects
a seeded, scaled sleep *inside* the measurement, so a chaos rule with a
MOCK_ERR-style rank anchor makes exactly that host's legs look slow and
the master's 2x-median rule (the straggler blamer's constant) does the
rest. ``MOCK_ERR_RANK`` itself is honored too: the anchored host's
probe reports an error and the gate refuses it, mirroring node_check.
"""

from __future__ import annotations

import os
import socket
import time

from dlrover_tpu.common.chaos import chaos_point
from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

PROBE_LEGS = ("hbm", "matmul", "collective")

# leg sizing (env-overridable: chaos arms shrink them, soak arms grow
# them). Defaults keep the whole CPU smoke-arm probe well under the 5 s
# join-overhead budget the bad-host schedule asserts.
HBM_BYTES = int(os.environ.get("DLROVER_PROBE_HBM_BYTES", str(1 << 24)))
HBM_ROUNDS = int(os.environ.get("DLROVER_PROBE_HBM_ROUNDS", "4"))
MATMUL_SIZE = int(os.environ.get("DLROVER_PROBE_MATMUL_SIZE", "256"))
MATMUL_ROUNDS = int(os.environ.get("DLROVER_PROBE_MATMUL_ROUNDS", "4"))
COLLECTIVE_BYTES = int(
    os.environ.get("DLROVER_PROBE_COLLECTIVE_BYTES", str(1 << 20))
)
COLLECTIVE_ROUNDS = int(
    os.environ.get("DLROVER_PROBE_COLLECTIVE_ROUNDS", "8")
)

# re-probe cadence: a quarantined host re-probes on the master's
# backoff schedule; an ADMITTED host re-probes in band at this floor
# cadence (stretched by the cost governor below, never tightened)
REPROBE_INTERVAL_S = float(
    os.environ.get("DLROVER_PROBE_REPROBE_INTERVAL", "600")
)
# the in-band re-probe's steady-state overhead budget, as a percent of
# the interval it rides — same contract (and default) as the device-
# time sampler's window governor (common/profiling.py)
REPROBE_OVERHEAD_PCT = float(
    os.environ.get("DLROVER_PROBE_OVERHEAD_PCT", "2.0")
)


def _node_rank() -> int:
    try:
        return int(os.environ.get(NodeEnv.NODE_RANK, "0"))
    except ValueError:
        return 0


def _mock_error() -> bool:
    """MOCK_ERR_RANK=<node_rank> makes that node's probe error out —
    the same injection contract node_check honors."""
    mock_rank = os.environ.get(NodeEnv.MOCK_ERR_RANK, "")
    return mock_rank != "" and mock_rank == os.environ.get(
        NodeEnv.NODE_RANK, "0"
    )


def _device_backend() -> str:
    """Accelerator backend name, or '' for the host stand-in path.
    Import failures gate to the stand-ins instead of erroring: the
    probe must run on smoke arms with no jax at all."""
    try:
        import jax

        backend = jax.default_backend()
        if backend != "cpu" and jax.local_devices():
            return backend
    except Exception:  # noqa: BLE001 - no jax / no devices -> host arm
        pass
    return ""


# ---------------------------------------------------------------- legs


def hbm_probe(rank: int, device: bool) -> float:
    """HBM-bandwidth leg: on-device copy rounds (host memcpy on the
    smoke arm). Returns elapsed milliseconds.

    The warmup pass runs OUTSIDE the timed window: allocation and
    page-fault noise on a first touch is 2x-scale — big enough to trip
    the gate's 2x-median rule on a perfectly healthy host."""
    if device:
        import jax
        import jax.numpy as jnp

        x = jax.device_put(
            jnp.ones((HBM_BYTES // 4,), dtype=jnp.float32)
        )
        (x + 0.0).block_until_ready()  # warmup
        t0 = time.perf_counter()
        chaos_point("probe.degrade", leg="hbm", rank=rank)
        for _ in range(HBM_ROUNDS):
            x = x + 0.0
        x.block_until_ready()
    else:
        src = bytearray(HBM_BYTES)
        dst = bytearray(HBM_BYTES)  # preallocated: copies, no allocs
        dst[:] = src  # warmup (faults both buffers in)
        t0 = time.perf_counter()
        chaos_point("probe.degrade", leg="hbm", rank=rank)
        for _ in range(HBM_ROUNDS):
            dst[:] = src
    return (time.perf_counter() - t0) * 1000.0


def matmul_probe(rank: int, device: bool) -> float:
    """MXU leg: a matmul round per local device (numpy on the smoke
    arm — a jitted CPU matmul would time XLA compilation instead).
    Returns elapsed milliseconds. Warmup outside the window (lazy BLAS
    init / XLA compile must not read as slow hardware)."""
    if device:
        import jax
        import jax.numpy as jnp

        xs = [
            jax.device_put(
                jnp.ones(
                    (MATMUL_SIZE, MATMUL_SIZE), dtype=jnp.bfloat16
                ),
                dev,
            )
            for dev in jax.local_devices()
        ]
        (jnp.matmul(xs[0], xs[0]) / MATMUL_SIZE).block_until_ready()
        t0 = time.perf_counter()
        chaos_point("probe.degrade", leg="matmul", rank=rank)
        for x in xs:
            for _ in range(MATMUL_ROUNDS):
                x = jnp.matmul(x, x) / MATMUL_SIZE
            x.block_until_ready()
    else:
        import numpy as np

        x = np.ones((MATMUL_SIZE, MATMUL_SIZE), dtype=np.float32)
        (x @ x) / MATMUL_SIZE  # warmup
        t0 = time.perf_counter()
        chaos_point("probe.degrade", leg="matmul", rank=rank)
        for _ in range(MATMUL_ROUNDS):
            x = (x @ x) / MATMUL_SIZE
    return (time.perf_counter() - t0) * 1000.0


def collective_probe(rank: int, device: bool) -> float:
    """ICI leg: psum rounds over the local mesh (loopback-socket round
    trips on the smoke arm — the one stand-in that still pushes bytes
    through a real network stack). Returns elapsed milliseconds.
    Setup and a warmup round run outside the timed window (pmap
    compilation / socket handshake are not the hardware under test)."""
    if device:
        import jax
        import jax.numpy as jnp

        devices = jax.local_devices()
        n = len(devices)
        shape = (n, max(COLLECTIVE_BYTES // 4 // max(n, 1), 1))
        x = jnp.ones(shape, dtype=jnp.float32)
        probe = jax.pmap(
            lambda v: jax.lax.psum(v, axis_name="d"),
            axis_name="d",
            devices=devices,
        )
        probe(x).block_until_ready()  # warmup (compile)
        t0 = time.perf_counter()
        chaos_point("probe.degrade", leg="collective", rank=rank)
        out = x
        for _ in range(COLLECTIVE_ROUNDS):
            out = probe(x)
        out.block_until_ready()
        return (time.perf_counter() - t0) * 1000.0
    server, sender, conn = _loopback_pair()
    try:
        _loopback_rounds(sender, conn, 1)  # warmup
        t0 = time.perf_counter()
        chaos_point("probe.degrade", leg="collective", rank=rank)
        _loopback_rounds(sender, conn, COLLECTIVE_ROUNDS)
        return (time.perf_counter() - t0) * 1000.0
    finally:
        sender.close()
        conn.close()
        server.close()


def _loopback_pair():
    """A connected 127.0.0.1 socket pair (server, sender, receiver)."""
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        server.bind(("127.0.0.1", 0))
        server.listen(1)
        sender = socket.create_connection(
            server.getsockname(), timeout=10
        )
        conn, _ = server.accept()
    except Exception:
        server.close()
        raise
    return server, sender, conn


def _loopback_rounds(sender, conn, rounds: int):
    """Push COLLECTIVE_BYTES through the pair per round — send and
    drain on the same thread in chunks small enough to never deadlock
    against the kernel buffers."""
    chunk = b"\x00" * 65536
    for _ in range(rounds):
        remaining = COLLECTIVE_BYTES
        while remaining > 0:
            part = chunk[: min(len(chunk), remaining)]
            sender.sendall(part)
            got = 0
            while got < len(part):
                got += len(conn.recv(len(part) - got))
            remaining -= len(part)


# --------------------------------------------------------------- probe


def run_probe(node_rank: int | None = None) -> dict:
    """Run all three legs; returns the join-payload report::

        {"legs": {"hbm": ms, "matmul": ms, "collective": ms},
         "elapsed_s": s, "host": rank, "backend": "tpu"|"host",
         "error": "", "t": wall}

    A leg failure (or MOCK_ERR_RANK) lands in ``error`` — the master's
    gate refuses hosts whose probe errored, exactly like node_check's
    binary fail. Never raises."""
    rank = _node_rank() if node_rank is None else int(node_rank)
    t0 = time.perf_counter()
    backend = _device_backend()
    legs: dict[str, float] = {}
    error = ""
    try:
        if _mock_error():
            raise RuntimeError(
                "mock probe failure injected via MOCK_ERR_RANK"
            )
        device = bool(backend)
        legs["hbm"] = round(hbm_probe(rank, device), 3)
        legs["matmul"] = round(matmul_probe(rank, device), 3)
        legs["collective"] = round(collective_probe(rank, device), 3)
    except Exception as e:  # noqa: BLE001 - a probe failure is a
        # verdict (refuse), not an agent crash
        logger.error("hardware probe failed: %s", e)
        error = str(e)
    elapsed = time.perf_counter() - t0
    report = {
        "legs": legs,
        "elapsed_s": round(elapsed, 4),
        "host": rank,
        "backend": backend or "host",
        "error": error,
        "t": time.time(),
    }
    logger.info(
        "hardware probe: %s (%.0f ms total)%s",
        {k: f"{v:.1f}ms" for k, v in legs.items()},
        elapsed * 1000,
        f" ERROR={error}" if error else "",
    )
    return report


class ProbeScheduler:
    """Cadence governor for the agent's in-band re-probe, mirroring the
    device-time sampler's window governor: ``interval`` is the FLOOR,
    and each probe's measured cost stretches the next gap until the
    steady-state overhead stays under ``overhead_pct`` of the wait — an
    always-on health signal that self-limits instead of taxing the
    monitor loop. The join-time report seeds the cache so a fresh join
    never immediately re-pays the probe."""

    def __init__(
        self,
        interval_s: float | None = None,
        overhead_pct: float | None = None,
    ):
        self.interval = float(
            REPROBE_INTERVAL_S if interval_s is None else interval_s
        )
        frac = (
            REPROBE_OVERHEAD_PCT if overhead_pct is None else overhead_pct
        )
        self._overhead_frac = max(float(frac), 0.0) / 100.0
        self._next_t = 0.0
        self.last_report: dict | None = None
        self.last_gap = self.interval

    def seed(self, report: dict, now: float | None = None):
        """Adopt a join-time report as the freshest sample."""
        now = time.time() if now is None else now
        self.last_report = report
        self._arm(float(report.get("elapsed_s", 0.0)), now)

    def _arm(self, cost_s: float, now: float):
        gap = self.interval
        if self._overhead_frac > 0 and cost_s > 0:
            gap = max(gap, cost_s / self._overhead_frac)
        self.last_gap = gap
        self._next_t = now + gap

    def due(self, now: float | None = None) -> bool:
        now = time.time() if now is None else now
        return now >= self._next_t

    def run(self, node_rank: int | None = None) -> dict:
        """Run the re-probe now and re-arm from its measured cost."""
        report = run_probe(node_rank)
        self.seed(report)
        return report


def probe_disabled() -> bool:
    """DLROVER_PROBE_DISABLE=1 skips the probe entirely: joins carry an
    empty report, which the master's gate admits (pre-health-plane
    behavior) — the opt-out for arms where even milliseconds matter."""
    return os.environ.get("DLROVER_PROBE_DISABLE", "") == "1"


_SCHEDULER: ProbeScheduler | None = None


def default_scheduler() -> ProbeScheduler:
    """The process-wide scheduler: the rendezvous handlers (elastic
    training AND network check) and the monitor loop share one cache,
    so back-to-back joins don't each re-pay the probe."""
    global _SCHEDULER
    if _SCHEDULER is None:
        _SCHEDULER = ProbeScheduler()
    return _SCHEDULER


def main():
    report = run_probe()
    raise SystemExit(0 if not report["error"] else 1)


if __name__ == "__main__":
    main()
