"""Per-host elastic training agent.

Equivalent capability: reference dlrover/python/elastic_agent/torch/
training.py — ElasticTrainingAgent (:346) with master-driven rendezvous
(MasterRendezvousHandler :165), run loop (_invoke_run :544: monitor
workers, save-checkpoint-then-restart on failure :589, membership-change
restart :602), launcher (launch_agent :673), ElasticLaunchConfig (:107);
NetworkCheckElasticAgent (:783) running probe rounds and reporting to the
master's pairing logic.

TPU redesign: worker processes are JAX processes; the rendezvous hands
them a JAX coordination-service address (env contract NodeEnv.JAX_*)
instead of a torch TCPStore; the node check payload is the ICI/DCN probe
in agent/node_check.py; failure taxonomy maps process exit codes AND
XLA/libtpu error patterns to hardware-vs-software errors.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import time

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common import flight, telemetry, tracing
from dlrover_tpu.common.chaos import chaos_point
from dlrover_tpu.agent.monitor import (
    HeartbeatReporter,
    ResourceMonitor,
    TelemetryReporter,
    TimerRingExporter,
)
from dlrover_tpu.agent.paral_config_tuner import ParalConfigTuner
from dlrover_tpu.common.constants import (
    ConfigPath,
    ExitCode,
    JobConstant,
    NodeEnv,
    RendezvousName,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


def apply_compilation_cache_env(cache_dir: str, env: dict) -> dict:
    """Point a worker env at the persistent XLA compilation cache.

    User-provided values win; the thresholds drop to "cache everything"
    so a restarted worker replays every program from cache instead of
    recompiling (the recompile-after-membership-change cost is the
    goodput sink the cache exists to remove)."""
    if cache_dir:
        os.makedirs(cache_dir, exist_ok=True)
        env.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
        env.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.0"
        )
        env.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1"
        )
    return env


@dataclasses.dataclass
class ElasticLaunchConfig:
    """Launch configuration (reference ElasticLaunchConfig :107)."""

    min_nodes: int = 1
    max_nodes: int = 1
    nproc_per_node: int = 1
    node_rank: int = 0
    max_restarts: int = 3
    monitor_interval: float = JobConstant.TRAINING_AGENT_LOOP_INTERVAL
    rdzv_timeout: float = JobConstant.RDZV_JOIN_TIMEOUT_DEFAULT
    # elastic (--nnodes lo:hi): how long the master waits for more
    # nodes beyond min before forming the world
    rdzv_elastic_wait: float = 30.0
    network_check: bool = False
    comm_perf_test: bool = False
    node_unit: int = 1
    auto_config: bool = False
    auto_tunning: bool = False
    exclude_straggler: bool = False
    save_at_breakpoint: bool = False
    accelerator: str = "tpu"
    log_dir: str | None = None
    run_id: str = "dlrover-tpu"
    # persistent XLA compilation cache shared across worker restarts:
    # elastic membership changes restart worker processes with a new
    # mesh, and the recompile must be a cache hit or it eats the goodput
    # the flash checkpoint bought (SURVEY hard-parts list). "" disables.
    compilation_cache_dir: str = "/tmp/dlrover_tpu/compile_cache"
    # Prometheus /metrics endpoint on the agent (reference xpu_timer
    # brpc/Prometheus export): -1 = disabled (default: an HTTP listener
    # is opt-in), 0 = ephemeral port, >0 = fixed port
    metrics_port: int = -1
    # how long one ride-through attempt waits for an unreachable master
    # to come back before logging the outage as (still) lost; workers
    # keep training either way and the agent re-probes on its next tick
    master_ride_through: float = JobConstant.MASTER_RIDE_THROUGH_DEFAULT
    # restart-free elasticity: on a membership change where the master's
    # verdict for this node is "reshape" AND every local worker
    # advertised a reshape watcher, signal the live workers to rebuild
    # their mesh in process instead of restarting them. Workers without
    # a watcher (or a failed/timed-out reshape) keep the classic
    # restart path, so this is safe to leave on.
    reshape_in_process: bool = True
    # how long the agent waits for all local workers to ack an
    # in-process reshape before falling back to the restart path
    reshape_ack_timeout: float = 60.0

    def auto_configure_params(self):
        """--auto-config: infer process count from visible devices."""
        if not self.auto_config:
            return
        try:
            import jax

            # One JAX process per host drives all local TPU chips.
            self.nproc_per_node = 1
            _ = jax.local_devices()
        except Exception:  # noqa: BLE001
            self.nproc_per_node = max(self.nproc_per_node, 1)


class WorkerSpec:
    def __init__(self, entrypoint: str, args: tuple, config: ElasticLaunchConfig):
        self.entrypoint = entrypoint
        self.args = args
        self.config = config


def world_rank_offset(world: dict, node_rank: int) -> int:
    """Global-rank offset of ``node_rank`` in a formed world: the local
    world sizes of every lower rank, summed in sorted order. One
    definition shared by spawn-time rank assignment and reshape-time
    signaling — the two must never disagree on a worker's global rank."""
    return sum(
        size
        for rank, size in sorted(world.items())
        if rank < node_rank
    )


class MasterRendezvousHandler:
    """Joins the master rendezvous and polls for the formed world
    (reference MasterRendezvousHandler :165)."""

    def __init__(
        self,
        name: str,
        node_rank: int,
        client: MasterClient,
        local_world_size: int,
        timeout: float,
        verified_step_fn=None,
        probe_scheduler=None,
    ):
        self._name = name
        self._node_rank = node_rank
        self._client = client
        self._local_world_size = local_world_size
        self._timeout = timeout
        # hardware-probe cadence cache (agent/probe.py): joins ship the
        # freshest per-leg timings; the process-wide default means a
        # net-check round and the training join share one probe
        from dlrover_tpu.agent.probe import default_scheduler

        self._probe = (
            probe_scheduler if probe_scheduler is not None
            else default_scheduler()
        )
        # callable -> list of locally-restorable checkpoint steps,
        # reported at join for the master's restore consensus (the
        # master forces only a step common to EVERY member)
        self._verified_step_fn = verified_step_fn
        # consensus the master broadcast with the latest formed world
        self.last_restore_step = -1

    def _local_verified_steps(self) -> list[int]:
        if self._verified_step_fn is None:
            return []
        try:
            return sorted(
                {int(s) for s in self._verified_step_fn() if int(s) >= 0},
                reverse=True,
            )
        except Exception:  # noqa: BLE001 - reporting steps is best-
            # effort; a scan error must not block the rendezvous
            logger.warning(
                "verified-step scan failed; joining without one",
                exc_info=True,
            )
            return []

    def next_rendezvous(self):
        """Returns (round, world, rank_offset, total_world, coordinator)."""
        # root span of the round's trace: every join/poll RPC under it
        # propagates this context, so the master-side join/form spans
        # nest under it — one cross-host tree per rendezvous round
        with tracing.span(
            "rdzv.round", rank=self._node_rank, rdzv=self._name
        ):
            return self._next_rendezvous()

    def _probe_report(self, fresh: bool = False) -> dict:
        """The hardware probe report to ship with a join: the cached
        sample while it is fresh, a re-run when the gate demanded one
        (``fresh``) or nothing is cached yet. Empty when disabled."""
        from dlrover_tpu.agent import probe as hw_probe

        if hw_probe.probe_disabled():
            return {}
        if fresh or self._probe.last_report is None:
            return self._probe.run(self._node_rank)
        return self._probe.last_report

    def _next_rendezvous(self):
        t0 = time.monotonic()
        verified_steps = self._local_verified_steps()
        newest = verified_steps[0] if verified_steps else -1
        # probe BEFORE the join: the master's health gate judges these
        # per-leg timings against the fleet and this host's own history
        probe_report = self._probe_report()
        joined = self._client.join_rendezvous(
            self._node_rank, self._local_world_size, self._name,
            verified_ckpt_step=newest,
            verified_ckpt_steps=verified_steps,
            probe_report=probe_report,
        )
        start = time.time()
        while True:
            if not joined:
                # the master acked False (its join handler faulted —
                # e.g. an injected rdzv.join drop): the node was never
                # recorded as waiting, so re-send the join or this node
                # polls an empty world until the timeout
                joined = self._client.join_rendezvous(
                    self._node_rank, self._local_world_size, self._name,
                    verified_ckpt_step=newest,
                    verified_ckpt_steps=verified_steps,
                    probe_report=probe_report,
                )
            world = self._client.get_comm_world(self._name, self._node_rank)
            if world and world.world and self._node_rank in world.world:
                break
            # an acked join with no world forming is EITHER a round
            # still filling or this host parked at the health gate —
            # only the verdict poll can tell them apart
            verdict = self._client.get_node_health(self._node_rank)
            if verdict.verdict in ("quarantine", "refuse"):
                remaining = start + self._timeout - time.time()
                wait = max(min(verdict.retry_after_s, remaining), 1.0)
                if remaining <= wait:
                    raise TimeoutError(
                        f"rendezvous {self._name}: host "
                        f"{self._node_rank} {verdict.verdict}d by the "
                        f"health gate ({verdict.reason}) and the "
                        f"backoff outlives the {self._timeout}s window"
                    )
                logger.warning(
                    "health gate %sd this host (%s); re-probing in "
                    "%.0fs (strike %d)",
                    verdict.verdict, verdict.reason, wait,
                    verdict.strikes,
                )
                telemetry.event(
                    "probe." + verdict.verdict,
                    rank=self._node_rank,
                    reason=verdict.reason,
                    retry_after_s=wait,
                    strikes=verdict.strikes,
                )
                # wait out the backoff, then re-join with a FRESH
                # probe — the gate re-serves the standing verdict to
                # anything staler
                time.sleep(wait)
                probe_report = self._probe_report(fresh=True)
                joined = self._client.join_rendezvous(
                    self._node_rank, self._local_world_size, self._name,
                    verified_ckpt_step=newest,
                    verified_ckpt_steps=verified_steps,
                    probe_report=probe_report,
                )
                continue
            if time.time() - start > self._timeout:
                raise TimeoutError(
                    f"rendezvous {self._name} timed out after "
                    f"{self._timeout}s (world={getattr(world, 'world', None)})"
                )
            time.sleep(1)
        rank_offset = world_rank_offset(world.world, self._node_rank)
        total = sum(world.world.values())
        # Rendezvous can block for the whole elastic-wait window; reset
        # stall clocks in THIS process so the wait is not read as a
        # hang. Scope note: detectors live per-process, so this covers
        # in-process/standalone trainers that drive a rendezvous
        # handler directly; subprocess workers are restarted after a
        # rendezvous and start with fresh clocks anyway (and their
        # restore path resets via Trainer.maybe_resume).
        from dlrover_tpu.trainer.fault_tolerance import (
            notify_progress_reset,
        )

        notify_progress_reset("rendezvous-resume")
        self.last_restore_step = getattr(world, "restore_step", -1)
        telemetry.event(
            "rdzv.wait",
            dur=time.monotonic() - t0,
            name=self._name,
            round=world.round,
            world=len(world.world),
            restore_step=self.last_restore_step,
        )
        return world.round, world.world, rank_offset, total, world.coordinator_addr


class WorkerProcess:
    def __init__(self, proc: subprocess.Popen, local_rank: int, global_rank: int):
        self.proc = proc
        self.local_rank = local_rank
        self.global_rank = global_rank

    @property
    def returncode(self):
        return self.proc.poll()


# XLA/libtpu stderr patterns that indicate a device (hardware) problem
# rather than a user-code bug — the TPU analogue of the reference's
# exit-code taxonomy (training.py:353-356).
_DEVICE_ERROR_PATTERNS = (
    "XlaRuntimeError: INTERNAL",
    "libtpu.so",
    "TPU initialization failed",
    "Unable to initialize backend",
    "DEADLINE_EXCEEDED",
    "device or resource busy",
)


def classify_exit(
    returncode: int, log_tail: str = "", stopping: bool = False,
    draining: bool = False,
) -> str:
    if returncode == 0:
        return "succeeded"
    if (stopping or draining) and (
        -returncode == signal.SIGTERM or returncode == ExitCode.TERMED
    ):
        # the AGENT sent that SIGTERM (stop/restart path): a worker
        # dying of it is a clean stop, not a software failure — it must
        # not burn a restart budget or be reported as a fault. The same
        # holds for a SIGTERM landing during an announced-preemption
        # drain: the teardown is the PLAN, not a failure — without the
        # draining flag this exact notice-then-SIGTERM shape was
        # charged as a software failure (and the ledger billed the
        # whole event to restart even when the drain succeeded).
        return "stopped"
    if draining and (
        -returncode in (signal.SIGKILL, signal.SIGTERM)
        or returncode in (ExitCode.KILLED, ExitCode.TERMED)
    ):
        # the platform's announced kill landed while (or after) the
        # drain ran: account it as the preemption it is — no restart
        # budget burned, no software-failure report
        return "preempted"
    if returncode in ExitCode.HARDWARE_ERRORS or -returncode in (
        signal.SIGABRT,
        signal.SIGBUS,
    ):
        return "hardware"
    if any(p in log_tail for p in _DEVICE_ERROR_PATTERNS):
        return "hardware"
    if returncode == ExitCode.OOM or -returncode == signal.SIGKILL:
        return "oom"
    return "software"


class ElasticTrainingAgent:
    """Runs and supervises the local worker processes of one node."""

    def __init__(
        self,
        config: ElasticLaunchConfig,
        spec: WorkerSpec,
        client: MasterClient,
    ):
        self._config = config
        self._spec = spec
        self._client = client
        self._workers: list[WorkerProcess] = []
        self._restart_count = 0
        self._remaining_restarts = config.max_restarts
        self._rdzv_handler = MasterRendezvousHandler(
            RendezvousName.ELASTIC_TRAINING,
            config.node_rank,
            client,
            config.nproc_per_node,
            config.rdzv_timeout,
            verified_step_fn=self._restorable_steps,
        )
        self._heartbeat = HeartbeatReporter(client)
        self._resource_monitor = ResourceMonitor(client)
        self._telemetry_reporter = TelemetryReporter(client)
        self._paral_tuner = ParalConfigTuner(client) \
            if config.auto_tunning else None
        self._timer_exporter = TimerRingExporter()
        self._log_files: list[str] = []
        self._ckpt_saver = None
        # set while the agent itself is terminating workers, so their
        # -SIGTERM exits classify as "stopped" instead of "software"
        self._stopping = False
        # set once an announced-preemption drain ran (the run loop
        # returns right after, so this is observable state for tests
        # and the exit taxonomy, not a loop flag)
        self._draining = False
        self._start_mono = time.monotonic()
        # True while the current contiguous hang-diagnosis episode has
        # already been flight-dumped (one artifact per episode, not one
        # per monitor tick); cleared when the verdict clears
        self._hang_episode_dumped = False
        # restart-free elasticity: the rendezvous round the running
        # workers were spawned into (or last reshaped to), and the
        # per-local-rank agent<->worker reshape channels
        self._last_round = -1
        self._reshape_channels: dict[int, object] = {}
        # deep-profiling capture channels (agent <-> worker), plus the
        # one background executor thread — the master's one-in-flight
        # discipline means at most one capture runs here at a time
        self._capture_channels: dict[int, object] = {}
        self._capture_thread = None
        self._capture_inflight = ""

    # ----------------------------------------------------------- lifecycle

    def _restorable_steps(self) -> list[int]:
        """The checkpoint steps this host could restore right now:
        verified storage steps, plus the shm step — but the latter only
        on single-host jobs, because a multi-host sharded engine dedups
        replicated leaves to one writer and a host's shm may then be
        target-incomplete (its restore path would refuse it), so
        advertising it could broadcast a consensus step some host
        cannot actually load. Reported at rendezvous join; the master
        forces the newest step common to every member."""
        from dlrover_tpu.agent.ckpt_saver import (
            AsyncCheckpointSaver,
            SharedMemoryHandler,
            verified_storage_steps,
        )

        saver = self._ckpt_saver or AsyncCheckpointSaver.get_ckpt_saver()
        if saver is None:
            return []
        steps: set[int] = set()
        if saver.num_hosts <= 1:
            for local_rank in range(saver.local_shard_num):
                # throwaway handler: the saver's own handlers may be in
                # use by a concurrent persist thread
                handler = SharedMemoryHandler(local_rank)
                try:
                    if handler.attach():
                        step = handler.get_checkpoint_step()
                        if step >= 0:
                            steps.add(step)
                finally:
                    handler.close()
        if saver.checkpoint_dir:
            steps.update(verified_storage_steps(saver.checkpoint_dir))
        return sorted(steps, reverse=True)

    def _initialize_workers(self):
        rdzv_round, world, rank_offset, total, coordinator = (
            self._rdzv_handler.next_rendezvous()
        )
        logger.info(
            "rendezvous round %s: world=%s rank_offset=%s total=%s "
            "restore_step=%s",
            rdzv_round,
            world,
            rank_offset,
            total,
            self._rdzv_handler.last_restore_step,
        )
        self._last_round = rdzv_round
        self._start_worker_processes(rank_offset, total, coordinator)

    def _worker_env(self, local_rank: int, global_rank: int, total: int, coordinator: str):
        env = dict(os.environ)
        # Workers must import dlrover_tpu no matter where their script
        # lives — propagate the framework's location.
        import dlrover_tpu

        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(dlrover_tpu.__file__))
        )
        existing = env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = (
                f"{pkg_root}{os.pathsep}{existing}" if existing else pkg_root
            )
        # Job identity scopes shm segment names: stable across worker
        # restarts of THIS job, distinct between jobs (a stale segment
        # from a previous job must never be restored). The agent sets the
        # same name in its own environ so the saver daemon and workers
        # resolve identical segment names.
        env.update(
            {
                NodeEnv.JOB_NAME: self._job_name(),
                NodeEnv.DLROVER_MASTER_ADDR: self._client.master_addr,
                NodeEnv.NODE_RANK: str(self._config.node_rank),
                NodeEnv.NODE_ID: str(self._client.node_id),
                NodeEnv.LOCAL_RANK: str(local_rank),
                NodeEnv.RANK: str(global_rank),
                NodeEnv.WORLD_SIZE: str(total),
                NodeEnv.LOCAL_WORLD_SIZE: str(self._config.nproc_per_node),
                NodeEnv.RESTART_COUNT: str(self._restart_count),
                NodeEnv.JAX_COORDINATOR_ADDR: coordinator,
                NodeEnv.JAX_PROCESS_ID: str(global_rank),
                NodeEnv.JAX_NUM_PROCESSES: str(total),
                ConfigPath.ENV_PARAL_CONFIG: ConfigPath.PARAL_CONFIG,
                ConfigPath.ENV_RUNTIME_METRICS: ConfigPath.RUNTIME_METRICS,
            }
        )
        # Telemetry: workers label their snapshots as role=worker (the
        # goodput ledger keys incarnation gaps off it), and the
        # master-brokered consensus restore step rides the env so the
        # engine restores exactly the agreed step.
        env[telemetry.ENV_ROLE] = "worker"
        if self._config.reshape_in_process:
            # per-worker reshape channel: a fresh incarnation must not
            # see the previous incarnation's request/ack/ready files
            from dlrover_tpu.trainer.elastic.reshape import (
                ReshapeChannel,
            )

            rdir = os.path.join(
                self._config.log_dir or "/tmp/dlrover_tpu/logs",
                f"reshape_{self._config.node_rank}_{local_rank}",
            )
            channel = ReshapeChannel(rdir)
            channel.clear()
            self._reshape_channels[local_rank] = channel
            env[NodeEnv.RESHAPE_DIR] = rdir
        # deep-capture channel: the worker's sampler polls it at step
        # boundaries; the agent relays master capture directives into
        # it. Per-incarnation like the reshape channel — a fresh
        # worker must not see a dead incarnation's request/ack.
        from dlrover_tpu.common import profiling

        cdir = os.path.join(
            self._config.log_dir or "/tmp/dlrover_tpu/logs",
            f"capture_{self._config.node_rank}_{local_rank}",
        )
        capture_channel = profiling.CaptureChannel(cdir)
        capture_channel.clear()
        self._capture_channels[local_rank] = capture_channel
        env[profiling.ENV_CAPTURE_DIR] = cdir
        restore_step = self._rdzv_handler.last_restore_step
        if restore_step >= 0:
            env[NodeEnv.RESTORE_STEP] = str(restore_step)
        else:
            env.pop(NodeEnv.RESTORE_STEP, None)
        apply_compilation_cache_env(
            self._config.compilation_cache_dir, env
        )
        return env

    def _start_worker_processes(self, rank_offset, total, coordinator):
        chaos_point(
            "agent.spawn",
            restart=self._restart_count,
            rank_offset=rank_offset,
        )
        telemetry.event(
            "worker.spawn",
            restart=self._restart_count,
            rank_offset=rank_offset,
            total=total,
        )
        self._workers = []
        self._log_files = []
        log_dir = self._config.log_dir or "/tmp/dlrover_tpu/logs"
        os.makedirs(log_dir, exist_ok=True)
        for local_rank in range(self._config.nproc_per_node):
            global_rank = rank_offset + local_rank
            env = self._worker_env(
                local_rank, global_rank, total, coordinator
            )
            if self._spec.entrypoint.endswith(".py"):
                cmd = [sys.executable, self._spec.entrypoint, *self._spec.args]
            else:
                cmd = [self._spec.entrypoint, *self._spec.args]
            log_path = os.path.join(
                log_dir,
                f"worker_{global_rank}_restart{self._restart_count}.log",
            )
            log_f = open(log_path, "ab")
            proc = subprocess.Popen(  # noqa: S603
                cmd,
                env=env,
                stdout=log_f,
                stderr=subprocess.STDOUT,
            )
            log_f.close()
            self._log_files.append(log_path)
            self._workers.append(
                WorkerProcess(proc, local_rank, global_rank)
            )
        logger.info(
            "started %d worker process(es), restart=%d",
            len(self._workers),
            self._restart_count,
        )

    def _stop_workers(self, timeout: float = 30.0):
        self._stopping = True
        try:
            for w in self._workers:
                if w.returncode is None:
                    w.proc.terminate()
            deadline = time.time() + timeout
            for w in self._workers:
                if w.returncode is None:
                    remaining = max(deadline - time.time(), 0.1)
                    try:
                        w.proc.wait(timeout=remaining)
                    except subprocess.TimeoutExpired:
                        w.proc.kill()
                        w.proc.wait()
            self._workers = []
        finally:
            self._stopping = False

    def _restart_workers(self):
        self._restart_count += 1
        self._stop_workers()
        self._initialize_workers()

    def _log_tail(self, idx: int, nbytes: int = 4096) -> str:
        try:
            path = self._log_files[idx]
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(size - nbytes, 0))
                return f.read().decode(errors="replace")
        except Exception:  # noqa: BLE001
            return ""

    def _save_ckpt_at_breakpoint(self):
        """Flush any checkpoint still in shared memory to storage before
        restarting (reference _save_ckpt_to_storage :589)."""
        from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

        saver = self._ckpt_saver or AsyncCheckpointSaver.get_ckpt_saver()
        if saver is not None:
            try:
                saver.save_shm_to_storage()
            except Exception:  # noqa: BLE001
                logger.exception("breakpoint checkpoint flush failed")

    def set_ckpt_saver(self, saver):
        self._ckpt_saver = saver

    def _cleanup_job_shm(self):
        """Unlink this job's checkpoint shm segments after a clean finish
        (they intentionally survive crashes, so nobody else reclaims
        them)."""
        from dlrover_tpu.agent.ckpt_saver import shm_name
        from dlrover_tpu.common.ipc import PersistentSharedMemory

        for local_rank in range(self._config.nproc_per_node):
            name = shm_name(local_rank)
            try:
                seg = PersistentSharedMemory(name=name)
                seg.close()
                seg.unlink()
            except FileNotFoundError:
                pass
            except Exception:  # noqa: BLE001
                logger.warning("shm cleanup failed for %s", name)

    # ------------------------------------------------------------ run loop

    def run(self) -> int:
        from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

        # The agent hosts the async checkpoint-saver daemon so shm
        # checkpoints survive (and get flushed) when workers die.
        os.environ.setdefault(NodeEnv.JOB_NAME, self._job_name())
        AsyncCheckpointSaver.start_async_saving_ckpt()
        try:
            AsyncCheckpointSaver.register_signal_handlers()
        except ValueError:
            pass  # not the main thread (tests)
        # a preempted/SIGTERMed agent leaves its flight record (last
        # spans/events + thread stacks) before dying
        flight.install()
        self._heartbeat.start()
        self._resource_monitor.start()
        self._telemetry_reporter.start()
        self._timer_exporter.start()
        if self._config.metrics_port >= 0:
            from dlrover_tpu.agent.monitor import MetricsEndpoint

            self._metrics_endpoint = MetricsEndpoint(
                self._timer_exporter, port=self._config.metrics_port
            )
            try:
                self._metrics_endpoint.start()
            except OSError as e:  # port in use: log, don't kill the job
                logger.warning("metrics endpoint failed to bind: %s", e)
                self._metrics_endpoint = None
        else:
            self._metrics_endpoint = None
        if self._paral_tuner is not None:
            self._paral_tuner.start()
        try:
            self._initialize_workers()
            return self._invoke_run()
        finally:
            self._stop_workers()
            self._heartbeat.stop()
            self._resource_monitor.stop()
            self._telemetry_reporter.stop()
            self._timer_exporter.stop()
            if self._metrics_endpoint is not None:
                self._metrics_endpoint.stop()
            if self._paral_tuner is not None:
                self._paral_tuner.stop()
            # final best-effort publish: the post-run obs report (and
            # the master, while it still listens) must see the agent's
            # rendezvous/spawn tail even after an abrupt job end
            self._telemetry_reporter.report_once(swallow=True)
            telemetry.flush()

    def _job_name(self) -> str:
        return os.environ.get(NodeEnv.JOB_NAME) or "job_" + (
            self._client.master_addr.replace(".", "_").replace(":", "_")
        )

    def _invoke_run(self) -> int:
        while True:
            time.sleep(self._config.monitor_interval)
            codes = [w.returncode for w in self._workers]
            if all(c == 0 for c in codes):
                logger.info("all workers succeeded")
                try:
                    self._client.report_job_end(True)
                except ConnectionError:
                    pass  # master already gone; local outcome stands
                self._cleanup_job_shm()
                return 0
            failed = [
                (i, c) for i, c in enumerate(codes) if c not in (None, 0)
            ]
            if failed:
                idx, code = failed[0]
                tail = self._log_tail(idx)
                # NOTE draining never reaches this classify: the drain
                # path stops its workers synchronously and returns from
                # the loop in the same iteration. classify_exit's
                # draining arms serve platform integrations that
                # observe worker deaths after a notice out-of-band.
                kind = classify_exit(code, tail, stopping=self._stopping)
                if kind == "stopped":
                    continue  # our own SIGTERM; the stop path finishes it
                telemetry.event(
                    "worker.exit", local_rank=idx, rc=code,
                    exit_kind=kind, restart=self._restart_count,
                )
                logger.warning(
                    "worker %d exited rc=%s (%s)", idx, code, kind
                )
                try:
                    self._client.report_failure(
                        f"worker rc={code} kind={kind}: {tail[-1000:]}",
                        TrainingExceptionLevel.PROCESS_ERROR,
                        self._restart_count,
                    )
                except (ConnectionError, OSError):
                    # a worker death DURING a master outage must still
                    # be handled locally; the report is best-effort
                    logger.warning(
                        "could not report worker failure (master "
                        "unreachable)"
                    )
                if self._config.save_at_breakpoint:
                    self._save_ckpt_at_breakpoint()
                if kind in ("software", "oom") and self._remaining_restarts <= 0:
                    logger.error("restarts exhausted; failing node")
                    self._client.report_job_end(False, "restarts exhausted")
                    return 1
                if kind == "hardware":
                    # A device-level fault: exit with the hardware code so
                    # the master relaunches this node elsewhere.
                    logger.error("hardware-level fault; exiting agent")
                    return ExitCode.DEVICE_ERROR
                self._remaining_restarts -= 1
                self._restart_workers()
                continue
            # workers healthy: probe the master cheaply (single-attempt
            # ping) so a coordinator outage is detected and attributed
            # promptly, instead of surfacing one exhausted retry budget
            # at a time; the heartbeat's budget-exhaustion flag is the
            # slow-path backstop
            if self._heartbeat.master_unreachable or not self._client.ping():
                self._ride_through_master_outage()
            # master-side diagnosis: a hang verdict naming THIS host
            # triggers a local flight-recorder dump (the worker's own
            # detector may be the thing that's stuck)
            self._poll_diagnosis()
            # continuous hardware check: a governed low-cadence
            # re-probe (floor interval stretched until the probe costs
            # under its overhead budget) feeding the master's
            # fingerprint store — sustained degradation becomes a
            # hw_degraded verdict and a drain, not a mystery slowdown
            self._maybe_reprobe()
            # announced preemption: the platform (simulated by the
            # ``preempt.notice`` chaos action) says this host dies at a
            # deadline — relay to the brain and, when directed, drain
            # (checkpoint + drained departure + clean worker stop) so
            # the whole event lands in the reshape bucket. An
            # unconsumed/unannounced kill keeps the restart path.
            if self._poll_preempt_notice():
                logger.info(
                    "predictive drain complete; awaiting preemption"
                )
                return 0
            # check membership changes: a waiting node, or a round the
            # master already re-formed from carried-over survivors
            # (reshape-first elasticity forms rounds without survivors
            # re-joining, so waiting can drop back to 0 between ticks)
            if self._membership_changed():
                self._handle_membership_change()
            if self._heartbeat.action == "stop":
                logger.info("master asked this node to stop")
                self._stop_workers()
                return 0
            if self._heartbeat.action == "restart":
                self._heartbeat.action = ""
                self._restart_workers()

    def _maybe_reprobe(self):
        """In-band hardware re-probe on the shared scheduler's cadence;
        best-effort shipping to the master's fingerprint store."""
        from dlrover_tpu.agent import probe as hw_probe

        if hw_probe.probe_disabled():
            return
        sched = hw_probe.default_scheduler()
        if not sched.due():
            return
        report = sched.run(self._config.node_rank)
        try:
            self._client.report_probe(self._config.node_rank, report)
        except Exception:  # noqa: BLE001 - the health signal is
            # advisory; a dropped sample waits for the next window
            logger.warning("in-band probe report failed", exc_info=True)

    def _poll_diagnosis(self):
        """Best-effort: fetch the master's runtime verdicts; when a
        hang diagnosis names this host, dump the flight recorder once
        per episode so the post-mortem exists even if the stuck worker
        can never write its own. The same poll delivers deep-capture
        directives (``DiagnosisResult.capture``)."""
        try:
            result = self._client.get_diagnosis()
        except Exception:  # noqa: BLE001 - diagnosis is advisory
            return
        directive = getattr(result, "capture", None) or {}
        if directive.get("capture_id"):
            self._maybe_execute_capture(directive)
        hangs = getattr(result, "hangs", None) or {}
        info = hangs.get(self._config.node_rank)
        if info is None:
            self._hang_episode_dumped = False
            return
        if self._hang_episode_dumped:
            return
        self._hang_episode_dumped = True
        telemetry.event(
            "diagnosis.hang.received",
            rank=self._config.node_rank, **info,
        )
        flight.dump("hang-diagnosis", diagnosis=info)

    # ------------------------------------------------- deep captures

    def _maybe_execute_capture(self, directive: dict):
        """Run a master capture directive against local worker 0 (one
        device trace per host is the contract) in a background thread:
        the capture spans multiple worker steps and must not stall the
        monitor loop. The directive re-serves on every diagnosis poll
        while it stands, so the in-flight guard below also absorbs the
        re-serves."""
        import threading

        from dlrover_tpu.common import profiling

        cid = str(directive["capture_id"])
        if self._capture_inflight == cid or (
            self._capture_thread is not None
            and self._capture_thread.is_alive()
        ):
            return
        channel = self._capture_channels.get(0)
        if channel is None:
            try:
                self._client.report_capture_result(
                    cid, self._config.node_rank, False,
                    error="no worker capture channel",
                )
            except (ConnectionError, OSError):
                pass
            return
        self._capture_inflight = cid
        worker0 = self._workers[0] if self._workers else None

        def report_fn(capture_id, ok, artifact, summary, error):
            try:
                self._client.report_capture_result(
                    capture_id, self._config.node_rank, ok,
                    artifact=artifact, summary=summary, error=error,
                )
            except (ConnectionError, OSError):
                # the master re-serves the directive on the next poll;
                # the in-flight marker clears with the thread
                logger.warning("capture result report failed")

        def run():
            try:
                profiling.execute_capture(
                    directive, channel, report_fn,
                    alive_fn=(
                        (lambda: worker0.returncode is None)
                        if worker0 is not None else None
                    ),
                )
            except Exception:  # noqa: BLE001 - a capture bug must not
                # take the agent's monitor loop down
                logger.exception("capture execution failed")
            finally:
                self._capture_inflight = ""

        self._capture_thread = threading.Thread(
            target=run, name="capture-executor", daemon=True
        )
        self._capture_thread.start()

    # --------------------------------------------- announced preemptions

    def _poll_preempt_notice(self) -> bool:
        """Consume a pending preemption notice, relay it to the
        master's brain, and execute the directed predictive drain.
        Returns True when the drain ran (the agent should shut down
        gracefully and wait for the kill). Master unreachable or
        directive \"none\" leaves the unannounced-kill fallback path
        untouched."""
        from dlrover_tpu.common import chaos

        chaos_point(
            "preempt.notice", rank=self._config.node_rank,
            elapsed=time.monotonic() - self._start_mono,
        )
        notice = chaos.take_preempt_notice()
        if notice is None:
            return False
        deadline = float(notice.get("deadline", 0.0))
        lead = max(deadline - time.time(), 0.0)
        telemetry.event(
            "preempt.notice", rank=self._config.node_rank,
            lead=round(lead, 3), deadline=deadline,
        )
        logger.warning(
            "preemption notice: this host dies in %.2fs; asking the "
            "brain", lead,
        )
        directive = None
        try:
            directive = self._client.report_preempt_notice(
                self._config.node_rank, deadline, lead
            )
        except (ConnectionError, OSError):
            # master unreachable inside the lead window: the
            # unannounced-kill path (restart + checkpoint replay) is
            # the unchanged fallback
            logger.warning(
                "could not relay the preemption notice (master "
                "unreachable); the kill will land unannounced"
            )
        except Exception:  # noqa: BLE001 - advisory path
            logger.warning("preempt notice relay failed", exc_info=True)
        if directive is None or getattr(directive, "action", "") != "drain":
            return False
        self._execute_predrain(
            deadline, getattr(directive, "plan_id", "")
        )
        return True

    def _execute_predrain(self, deadline: float, plan_id: str):
        """The doomed host's half of a predictive-drain plan, ordered
        for maximal overlap with the survivors' reshape: (1) the drain
        report — survivors start reshaping around this host
        immediately; (2) flush the shm checkpoint to storage so the
        replacement resumes with zero replay; (3) stop workers cleanly
        before the platform kill lands. The ``elastic.drained`` marker
        is what re-charges the teardown gap from ``restart`` to
        ``reshape`` in the goodput ledger."""
        t0 = time.monotonic()
        self._draining = True
        try:
            self._client.drain_node(self._config.node_rank)
        except (ConnectionError, OSError):
            logger.warning(
                "drain report failed; survivors will see a dead "
                "departure instead"
            )
        self._save_ckpt_at_breakpoint()
        budget = max(deadline - time.time() - 1.0, 1.0)
        self._stop_workers(timeout=min(budget, 30.0))
        telemetry.event(
            "elastic.drained", rank=self._config.node_rank,
            plan=plan_id, dur=time.monotonic() - t0,
            deadline=deadline,
        )
        telemetry.flush()

    def _membership_changed(self) -> bool:
        try:
            waiting = self._client.num_nodes_waiting(
                RendezvousName.ELASTIC_TRAINING
            )
            if waiting > 0:
                return True
            # carried-over survivors never re-join, so the new round
            # can form (and waiting return to 0) entirely between two
            # monitor ticks — compare the formed round number too
            world = self._client.get_comm_world(
                RendezvousName.ELASTIC_TRAINING, self._config.node_rank
            )
            return bool(
                world and world.world and world.round != self._last_round
            )
        except (ConnectionError, OSError):
            # master unreachable, not a membership change: ride through
            # (workers keep training on their last formed world)
            self._ride_through_master_outage()
            return False
        except Exception:  # noqa: BLE001
            return False

    # ------------------------------------------- reshape-first elasticity

    def _workers_alive(self) -> bool:
        return bool(self._workers) and all(
            w.returncode is None for w in self._workers
        )

    def _workers_reshape_ready(self) -> bool:
        """Every local worker advertised a reshape watcher (the Trainer
        writes the ready marker when it installs one). Bare workers
        keep the classic restart path."""
        if not self._config.reshape_in_process:
            return False
        channels = [
            self._reshape_channels.get(w.local_rank)
            for w in self._workers
        ]
        return bool(channels) and all(
            c is not None and c.worker_ready() for c in channels
        )

    def _await_formed_world(self, timeout: float):
        """Poll the master until the NEXT round is formed with this
        node in it (polling is also what triggers formation once the
        waiting set is ready). None = timeout, excluded, or a worker
        died while waiting."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if not self._workers_alive():
                return None
            try:
                world = self._client.get_comm_world(
                    RendezvousName.ELASTIC_TRAINING,
                    self._config.node_rank,
                )
            except (ConnectionError, OSError):
                time.sleep(1.0)
                continue
            if world and world.world and world.round != self._last_round:
                if self._config.node_rank not in world.world:
                    return None
                return world
            time.sleep(0.5)
        return None

    def _handle_membership_change(self):
        """Reshape-first: when the master's verdict for this node is
        "reshape" and every local worker runs a reshape watcher, the
        membership change is signaled INTO the live workers (drain ->
        in-process mesh rebuild + reshard -> resume). Everything else
        — no watcher, verdict "restart", excluded from the round, a
        failed or timed-out reshape, a worker killed mid-reshape —
        falls back to the classic restart path."""
        if not self._workers_reshape_ready() or not self._workers_alive():
            logger.info("membership changed; restarting workers")
            self._restart_workers()
            return
        world = self._await_formed_world(
            min(self._config.rdzv_timeout, 120.0)
        )
        if world is None:
            logger.info(
                "membership changed but no new round formed with this "
                "node; restarting workers"
            )
            self._restart_workers()
            return
        verdict = (getattr(world, "verdicts", None) or {}).get(
            self._config.node_rank, "restart"
        )
        if verdict != "reshape":
            logger.info(
                "membership changed (verdict=%s); restarting workers",
                verdict,
            )
            self._restart_workers()
            return
        if self._signal_reshape(world):
            self._last_round = world.round
            telemetry.event(
                "elastic.reshape.adopted",
                round=world.round,
                world=len(world.world),
            )
            logger.info(
                "round %s adopted in process (no worker restart)",
                world.round,
            )
        else:
            logger.warning(
                "in-process reshape for round %s failed or timed out; "
                "falling back to the restart path", world.round,
            )
            self._restart_workers()

    def _signal_reshape(self, world) -> bool:
        """Write the reshape request to every local worker and wait for
        all acks. False = restart fallback required."""
        from dlrover_tpu.trainer.elastic.reshape import ReshapeRequest

        request = ReshapeRequest(
            round=world.round,
            world=world.world,
            rank_offset=world_rank_offset(
                world.world, self._config.node_rank
            ),
            total=sum(world.world.values()),
            coordinator=world.coordinator_addr,
            departed=dict(getattr(world, "departed", None) or {}),
        )
        try:
            for w in self._workers:
                self._reshape_channels[w.local_rank].signal(request)
            deadline = time.time() + self._config.reshape_ack_timeout
            for w in self._workers:
                channel = self._reshape_channels[w.local_rank]
                ack = channel.await_ack(
                    world.round,
                    max(deadline - time.time(), 0.1),
                    alive_fn=lambda w=w: w.returncode is None,
                )
                if ack is None or not ack.get("ok"):
                    return False
            return True
        except Exception:  # noqa: BLE001 - the signal write is itself
            # a fault seam (elastic.signal chaos site, ENOSPC on the
            # request file): a failed signal must DEGRADE to the
            # restart path, never crash the agent out of its monitor
            # loop with workers still running
            logger.exception(
                "reshape signaling for round %s failed; falling back "
                "to the restart path", world.round,
            )
            return False

    # ------------------------------------------------- master ride-through

    def _ride_through_master_outage(self):
        """The master is gone (every retry budget exhausted). Workers
        keep training — only data-plane collectives involve them, and
        shard fetches ride their own retry policies — while this agent
        polls for the master (old or restarted, re-resolving the
        address each probe) and re-registers when it answers. Only a
        GENUINE membership change reported by the restored master
        triggers a worker restart, via the normal num_nodes_waiting
        path after this returns."""
        t0 = time.monotonic()
        telemetry.event(
            "master.unreachable", restart=self._restart_count
        )
        logger.warning(
            "master unreachable at %s; riding through (workers keep "
            "training)", self._client.master_addr,
        )
        ok = self._client.await_master(
            timeout=self._config.master_ride_through
        )
        dur = time.monotonic() - t0
        if not ok:
            telemetry.event("master.lost", dur=dur)
            logger.error(
                "master still unreachable after %.0fs; workers keep "
                "training, will re-probe next tick", dur,
            )
            return
        # the outage interval: the goodput ledger charges it to the
        # ``restart`` bucket (anything workers productively overlapped
        # still wins by sweep priority)
        telemetry.event(
            "master.restart", dur=dur, addr=self._client.master_addr
        )
        logger.info(
            "master back after %.1fs at %s; re-registering",
            dur, self._client.master_addr,
        )
        self._heartbeat.reset_misses()
        self._re_register()

    def _re_register(self):
        """Re-push the state a restored master may be missing: node
        meta, the newest locally-restorable checkpoint steps (persists
        during the outage aren't in its snapshot), and this host's
        telemetry. Deliberately NOT a rendezvous join — that would
        dissolve the restored round and restart healthy workers."""
        try:
            self._client.report_node_meta(
                self._config.node_rank, addr=self._client.host_ip
            )
            self._client.report_verified_steps(
                self._config.node_rank, self._restorable_steps()
            )
        except (ConnectionError, OSError):
            logger.warning(
                "post-outage re-registration failed; next tick retries"
            )
        except Exception:  # noqa: BLE001 - best-effort: a scan error
            # must not take down a healthy agent
            logger.warning("post-outage re-registration error",
                           exc_info=True)
        self._telemetry_reporter.reset_shipped()
        self._telemetry_reporter.report_once(swallow=True)


class NodeCheckElasticAgent:
    """Runs probe rounds + reports to the master's pairing logic
    (reference NetworkCheckElasticAgent :783)."""

    def __init__(
        self, config: ElasticLaunchConfig, client: MasterClient, rounds=2
    ):
        self._config = config
        self._client = client
        self._rounds = rounds
        self._rdzv_handler = MasterRendezvousHandler(
            RendezvousName.NETWORK_CHECK,
            config.node_rank,
            client,
            config.nproc_per_node,
            config.rdzv_timeout,
        )

    def _wait_round_verdict(self, timeout: float):
        """Poll until every node of the round reported (the master stops
        answering 'Waiting node') or the timeout passes."""
        from dlrover_tpu.common.constants import NetworkFailureReason

        deadline = time.time() + timeout
        result = None
        while time.time() < deadline:
            result = self._client.check_network_ready()
            if result is not None and (
                result.normal
                or result.reason != NetworkFailureReason.WAITING_NODE
            ):
                break
            time.sleep(2)
        return result

    def run(self) -> bool:
        from dlrover_tpu.agent.node_check import run_node_check

        node_rank = self._config.node_rank
        round_timeout = min(self._config.rdzv_timeout, 90)
        result = None
        for _ in range(self._rounds):
            self._rdzv_handler.next_rendezvous()
            normal, elapsed = run_node_check()
            self._client.report_node_check_result(
                node_rank, normal, elapsed
            )
            result = self._wait_round_verdict(round_timeout)
            if result is not None and result.normal:
                if self._config.exclude_straggler:
                    straggler = self._client.check_straggler()
                    if straggler and node_rank in straggler.nodes:
                        logger.error(
                            "this node is a straggler; excluding"
                        )
                        return False
                return True
            if result is not None and node_rank in result.nodes:
                logger.error(
                    "node %s isolated as faulty by the master", node_rank
                )
                return False
            # round complete but undecided -> run another probe round
        if result is None:
            return False
        if node_rank in result.nodes:
            logger.error("node %s isolated as faulty", node_rank)
            return False
        if not result.normal:
            logger.warning(
                "network check inconclusive (%s); this node is not in the "
                "fault set, continuing",
                result.reason,
            )
        return True


_SHARED_CONFIG_KEYS = ("nproc_per_node", "network_check", "node_unit")


def _share_run_config(client: MasterClient, config: ElasticLaunchConfig,
                      wait: float = 30.0):
    """Flag consistency across hosts (reference auto_config sharing).

    Rank 0 publishes the launch flags that must match job-wide; later
    joiners poll for them (all hosts start concurrently, so a single
    fetch would race rank 0's publish) and adopt, so a fat-fingered
    per-host flag can't split the rendezvous world.
    """
    if config.node_rank == 0:
        client.report_elastic_run_config({
            k: getattr(config, k) for k in _SHARED_CONFIG_KEYS
        })
        return
    deadline = time.time() + wait
    published: dict = {}
    while time.time() < deadline:
        published = client.get_elastic_run_config()
        if published:
            break
        time.sleep(0.5)
    if not published:
        logger.warning(
            "rank 0 never published a run config within %.0fs; keeping "
            "local flags", wait,
        )
        return
    for key in _SHARED_CONFIG_KEYS:
        if key in published and published[key] != getattr(config, key):
            logger.warning(
                "adopting job-wide %s=%r (was %r)",
                key, published[key], getattr(config, key),
            )
            setattr(config, key, published[key])


def launch_agent(
    config: ElasticLaunchConfig,
    entrypoint: str,
    args: tuple,
    master_addr: str,
) -> int:
    """Build the client + agent and run (reference launch_agent :673)."""
    config.auto_configure_params()
    client = MasterClient(
        master_addr, config.node_rank, "worker"
    )
    _share_run_config(client, config)
    if config.min_nodes != config.max_nodes:
        # elastic --nnodes lo:hi: the master must form the world at
        # >= min after the waiting window instead of insisting on max
        client.report_rdzv_params(
            config.min_nodes, config.max_nodes,
            waiting_timeout=config.rdzv_elastic_wait,
            node_unit=config.node_unit,
        )
    if config.network_check:
        checker = NodeCheckElasticAgent(config, client)
        if not checker.run():
            logger.error("node check failed; aborting this node")
            return ExitCode.NETWORK_CHECK_FAILED
    agent = ElasticTrainingAgent(
        config, WorkerSpec(entrypoint, args, config), client
    )
    try:
        return agent.run()
    finally:
        client.close()
