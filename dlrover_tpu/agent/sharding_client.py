"""Worker-side data-shard consumption client.

Equivalent capability: reference dlrover/python/elastic_agent/sharding/
client.py — ShardingClient (:29) fetch/report loop with shard checkpoint
get/restore (:199-226) and IndexShardingClient (:231, per-sample index
queue).
"""

from __future__ import annotations

import queue
import threading
import time

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common import tracing
from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


class ShardingClient:
    """Fetches shard tasks from the master and reports completions."""

    def __init__(
        self,
        dataset_name: str,
        batch_size: int,
        num_epochs: int,
        dataset_size: int,
        shuffle: bool = False,
        task_type: str = "training",
        num_minibatches_per_shard: int = 2,
        storage_type: str = "",
        dataset_type: str = "table",
        master_client: MasterClient | None = None,
    ):
        self._client = master_client or MasterClient.singleton_instance()
        if self._client is None:
            raise RuntimeError(
                "no master client (DLROVER_MASTER_ADDR unset)"
            )
        self.dataset_name = dataset_name
        self._batch_size = batch_size
        self._lock = threading.Lock()
        self._current_task = None
        self._pending_tasks: list = []
        self._client.report_dataset_shard_params(
            batch_size=batch_size,
            num_epochs=num_epochs,
            dataset_size=dataset_size,
            shuffle=shuffle,
            num_minibatches_per_shard=num_minibatches_per_shard,
            dataset_name=dataset_name,
            task_type=task_type,
            storage_type=storage_type,
            dataset_type=dataset_type,
        )

    def fetch_shard(self, wait_interval: float = 1.0):
        """Returns the next Shard or None when the dataset is finished.

        Streaming datasets return WAIT tasks while momentarily dry; the
        client blocks (polling) until data arrives or the stream ends.
        """
        # fetch span roots the shard's trace: the master-side dispatch
        # span nests under it, and report_batch_done joins the same
        # trace via the task_id label
        with tracing.span("shard.fetch", dataset=self.dataset_name):
            while True:
                task = self._client.get_task(self.dataset_name)
                if task is not None and task.task_type == TaskType.WAIT:
                    time.sleep(wait_interval)
                    continue
                break
        if task is None or task.task_id < 0:
            return None
        with self._lock:
            self._current_task = task
            self._pending_tasks.append(task)
        return task.shard

    def report_batch_done(self, task_ids=None):
        """Report completion of the oldest pending task(s)."""
        with self._lock:
            if task_ids is None:
                if not self._pending_tasks:
                    return
                tasks = [self._pending_tasks.pop(0)]
            else:
                tasks = [
                    t
                    for t in self._pending_tasks
                    if t.task_id in task_ids
                ]
                self._pending_tasks = [
                    t
                    for t in self._pending_tasks
                    if t.task_id not in task_ids
                ]
        for t in tasks:
            with tracing.span("shard.report", task_id=t.task_id):
                self._client.report_task_result(
                    self.dataset_name, t.task_id
                )

    def report_all_pending_done(self):
        """Ack every pending shard task (end-of-epoch drain)."""
        with self._lock:
            tasks, self._pending_tasks = self._pending_tasks, []
        for t in tasks:
            self._client.report_task_result(self.dataset_name, t.task_id)

    def report_task_failed(self, task_id: int, err: str):
        self._client.report_task_result(self.dataset_name, task_id, err)

    # ---- mid-epoch checkpoint (sampler state across restarts) ------------

    def get_shard_checkpoint(self) -> str:
        return self._client.get_shard_checkpoint(self.dataset_name)

    def restore_shard_from_checkpoint(self, content: str) -> bool:
        return self._client.report_shard_checkpoint(content)


class IndexShardingClient(ShardingClient):
    """Hands out per-sample indices instead of whole shards (reference
    IndexShardingClient :231)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._sample_queue: queue.Queue = queue.Queue()

    def fetch_sample_index(self):
        """Next global sample index, or None at end of data."""
        if self._sample_queue.empty():
            shard = self.fetch_shard()
            if shard is None:
                return None
            indices = shard.record_indices or range(shard.start, shard.end)
            for i in indices:
                self._sample_queue.put(i)
        return self._sample_queue.get()

    def fetch_batch_indices(self, batch_size: int):
        indices = []
        for _ in range(batch_size):
            idx = self.fetch_sample_index()
            if idx is None:
                break
            indices.append(idx)
        return indices or None
