"""Group sparse optimizers: per-row adaptive state for embedding tables.

Equivalent capability: reference TFPlus sparse optimizers
(tfplus/tfplus/kv_variable/ops/training_ops.cc:103-571 — Group Adam /
Adagrad / FTRL apply kernels; Python wrappers python/training/
group_adam.py etc.). "Group" = each embedding row is an optimization
group: moments and bias-correction step counts advance only on steps
where the row was actually touched, so rarely-seen features keep
fresh adaptive scales instead of being decayed by millions of steps
they never participated in.

TPU redesign: rows touched in a step are exactly the rows with nonzero
gradient (gather autodiff produces zero rows elsewhere); the update is a
dense masked computation — XLA fuses the mask into the moment updates,
and everything shards row-wise over the mesh like the table itself.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class GroupAdamState(NamedTuple):
    steps: optax.Updates  # per-row update counts [rows, 1]
    mu: optax.Updates
    nu: optax.Updates


def _row_mask(g):
    """[rows, 1] float mask of rows with any nonzero gradient."""
    if g.ndim < 2:
        return (g != 0).astype(g.dtype)
    reduced = jnp.any(g != 0, axis=tuple(range(1, g.ndim)), keepdims=True)
    return reduced.astype(g.dtype)


def group_adam(
    learning_rate: float | optax.Schedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """Adam whose moments/bias-correction advance per-row (GroupAdam)."""

    def init_fn(params):
        def zeros_steps(p):
            if p.ndim == 0:
                return jnp.zeros((), jnp.int32)
            return jnp.zeros(
                (p.shape[0],) + (1,) * (p.ndim - 1), jnp.int32
            )

        return GroupAdamState(
            steps=jax.tree.map(zeros_steps, params),
            mu=jax.tree.map(jnp.zeros_like, params),
            nu=jax.tree.map(jnp.zeros_like, params),
        )

    def update_fn(updates, state, params=None):
        masks = jax.tree.map(_row_mask, updates)
        steps = jax.tree.map(
            lambda s, m: s + m.astype(jnp.int32), state.steps, masks
        )
        mu = jax.tree.map(
            lambda mo, g, m: jnp.where(
                m > 0, b1 * mo + (1 - b1) * g, mo
            ),
            state.mu, updates, masks,
        )
        nu = jax.tree.map(
            lambda v, g, m: jnp.where(
                m > 0, b2 * v + (1 - b2) * g * g, v
            ),
            state.nu, updates, masks,
        )

        def corrected(mo, v, s, m):
            t = jnp.maximum(s, 1).astype(mo.dtype)
            mo_hat = mo / (1 - b1**t)
            v_hat = v / (1 - b2**t)
            upd = mo_hat / (jnp.sqrt(v_hat) + eps)
            return jnp.where(m > 0, upd, jnp.zeros_like(upd))

        new_updates = jax.tree.map(corrected, mu, nu, steps, masks)
        if weight_decay:
            assert params is not None, "weight decay needs params"
            new_updates = jax.tree.map(
                lambda u, p, m: u + weight_decay * p * (m > 0),
                new_updates, params, masks,
            )
        return new_updates, GroupAdamState(steps=steps, mu=mu, nu=nu)

    return optax.chain(
        optax.GradientTransformation(init_fn, update_fn),
        optax.scale_by_learning_rate(learning_rate),
    )


class GroupAdagradState(NamedTuple):
    accum: optax.Updates


def group_adagrad(
    learning_rate: float | optax.Schedule = 1e-2,
    initial_accumulator: float = 0.1,
    eps: float = 1e-10,
) -> optax.GradientTransformation:
    """Adagrad with per-row accumulators (GroupAdagrad analogue)."""

    def init_fn(params):
        return GroupAdagradState(
            accum=jax.tree.map(
                lambda p: jnp.full_like(p, initial_accumulator), params
            ),
        )

    def update_fn(updates, state, params=None):
        del params
        masks = jax.tree.map(_row_mask, updates)
        accum = jax.tree.map(
            lambda a, g, m: jnp.where(m > 0, a + g * g, a),
            state.accum, updates, masks,
        )
        new_updates = jax.tree.map(
            lambda g, a, m: jnp.where(
                m > 0, g / (jnp.sqrt(a) + eps), jnp.zeros_like(g)
            ),
            updates, accum, masks,
        )
        return new_updates, GroupAdagradState(accum=accum)

    return optax.chain(
        optax.GradientTransformation(init_fn, update_fn),
        optax.scale_by_learning_rate(learning_rate),
    )
