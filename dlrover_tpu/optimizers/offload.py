"""Host-offload Adam: optimizer state lives in host memory, not HBM.

Equivalent capability: reference atorch/atorch/optimizers/adam_offload.py
(PartitionAdam — CPU-resident optimizer state updated with GPU grads).
TPU redesign: HBM holds only params (+ transient grads); the Adam
moments stay in pinned host numpy buffers. Each step streams the grads
device->host (``jax.device_get``), runs the vectorized Adam math on the
host, and streams the *updates* host->device (``jax.device_put`` onto
the params' own shardings). That trades HBM for PCIe/ICI-DCN traffic —
the same trade the reference makes — and frees 2x fp32 param bytes of
device memory, which is what lets a model one size up fit.

Not an optax transformation on purpose: an optax ``update`` runs inside
jit, where host state can't live. The step structure is
grads-on-device -> host update -> apply-on-device, all overlap-friendly
(device_get of leaf i overlaps the host math of leaf i-1).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


class OffloadAdamState(NamedTuple):
    count: int
    mu: list          # host f32 buffers, one per leaf
    nu: list


class OffloadAdam:
    """AdamW with host-resident moments.

    Usage::

        opt = OffloadAdam(1e-3, weight_decay=0.01)
        state = opt.init(params)                  # host buffers
        grads = jitted_grad_fn(params, batch)     # device
        params, state = opt.step(params, grads, state)
    """

    def __init__(self, learning_rate: float = 1e-3, b1: float = 0.9,
                 b2: float = 0.999, eps: float = 1e-8,
                 weight_decay: float = 0.0):
        self.lr = learning_rate
        self.b1 = b1
        self.b2 = b2
        self.eps = eps
        self.weight_decay = weight_decay

    def init(self, params) -> OffloadAdamState:
        import jax

        leaves = jax.tree.leaves(params)
        mu = [np.zeros(np.shape(p), np.float32) for p in leaves]
        nu = [np.zeros(np.shape(p), np.float32) for p in leaves]
        host_bytes = sum(b.nbytes for b in mu) * 2
        logger.info(
            "OffloadAdam: %.2f GB optimizer state on host",
            host_bytes / (1 << 30),
        )
        return OffloadAdamState(count=0, mu=mu, nu=nu)

    def step(self, params, grads, state: OffloadAdamState):
        """Apply one AdamW step. Returns (new_params, new_state); the
        updates are computed on host and placed back onto each param's
        own sharding.

        The moment buffers are updated IN PLACE (no per-step host
        reallocation of 2x param bytes): the returned state aliases the
        input state's buffers, so a previously-held ``OffloadAdamState``
        is not a snapshot — use :meth:`state_dict` (which copies) to
        checkpoint."""
        import jax

        leaves, treedef = jax.tree.flatten(params)
        grad_leaves = jax.tree.leaves(grads)
        # launch every D2H transfer before touching any bytes, so the
        # copy of leaf i+1 overlaps the host math of leaf i (same
        # pattern as the checkpoint engine's _write_shm_locked)
        for g in grad_leaves:
            if isinstance(g, jax.Array):
                g.copy_to_host_async()
        t = state.count + 1
        bc1 = 1.0 - self.b1**t
        bc2 = 1.0 - self.b2**t
        new_leaves = []
        for i, (p, g) in enumerate(zip(leaves, grad_leaves)):
            gh = np.asarray(jax.device_get(g), np.float32)
            mu = state.mu[i]
            nu = state.nu[i]
            mu *= self.b1
            mu += (1.0 - self.b1) * gh
            nu *= self.b2
            nu += (1.0 - self.b2) * np.square(gh)
            update = (mu / bc1) / (np.sqrt(nu / bc2) + self.eps)
            update = (-self.lr * update).astype(np.dtype(p.dtype))
            sharding = getattr(p, "sharding", None)
            upd_dev = (
                jax.device_put(update, sharding)
                if sharding is not None else jax.numpy.asarray(update)
            )
            # decoupled decay applied on device: no extra D2H of params
            if self.weight_decay:
                new_leaves.append(
                    p * (1.0 - self.lr * self.weight_decay) + upd_dev
                )
            else:
                new_leaves.append(p + upd_dev)
        new_params = jax.tree.unflatten(treedef, new_leaves)
        return new_params, OffloadAdamState(
            count=t, mu=state.mu, nu=state.nu
        )

    # ------------------------------------------------------- checkpoints

    def state_dict(self, state: OffloadAdamState) -> dict:
        return {
            "count": state.count,
            "mu": [b.copy() for b in state.mu],
            "nu": [b.copy() for b in state.nu],
        }

    def load_state_dict(self, d: dict) -> OffloadAdamState:
        return OffloadAdamState(
            count=int(d["count"]),
            mu=[np.asarray(b, np.float32) for b in d["mu"]],
            nu=[np.asarray(b, np.float32) for b in d["nu"]],
        )
