"""8-bit Adam: optimizer moments stored as block-quantized 8-bit codes.

Equivalent capability: reference atorch/atorch/optimizers/low_bit/ backed
by the CUDA kernels in atorch/atorch/ops/csrc/quantization/
(quantization_optimizer.cu — 8-bit Adam state with blockwise scales and
stochastic rounding). TPU redesign:

- the first moment (signed, moderate dynamic range) uses the Pallas
  linear-absmax int8 kernel with stochastic rounding (unbiased, so
  quantization noise doesn't bias the EMA);
- the second moment (non-negative, huge dynamic range) uses a log-spaced
  codebook (the analogue of the reference's nonlinear "dynamic" code):
  linear absmax would round small entries to zero and the Adam
  denominator would collapse to eps, exploding those coordinates.

Memory for optimizer state drops ~4x vs fp32 Adam — on HBM-bound TPU
training that directly buys larger batch or model shards.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.ops.quantization import (
    BLOCK,
    dequantize_int8,
    dequantize_pos_log,
    quantize_int8,
    quantize_pos_log,
)


class QuantizedMoment(NamedTuple):
    q: jnp.ndarray       # int8/uint8 [rows, BLOCK]
    scales: jnp.ndarray  # f32 [rows, 1]


def _rows_for(leaf) -> int:
    n = 1
    for d in leaf.shape:
        n *= d
    return -(-max(n, 1) // BLOCK)


def _zero_moment(leaf, dtype) -> QuantizedMoment:
    rows = _rows_for(leaf)
    return QuantizedMoment(
        q=jnp.zeros((rows, BLOCK), dtype),
        scales=jnp.ones((rows, 1), jnp.float32),
    )


class ScaleByAdam8bitState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates  # pytree of QuantizedMoment (int8 linear)
    nu: optax.Updates  # pytree of QuantizedMoment (uint8 log-code)


def scale_by_adam8bit(
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
) -> optax.GradientTransformation:
    def init_fn(params):
        # zeros quantize trivially: build the int8 state directly instead
        # of running quantization kernels over zero tensors
        return ScaleByAdam8bitState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(lambda p: _zero_moment(p, jnp.int8), params),
            nu=jax.tree.map(lambda p: _zero_moment(p, jnp.uint8), params),
        )

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        is_qm = lambda x: isinstance(x, QuantizedMoment)  # noqa: E731
        mu_f = jax.tree.map(
            lambda qm, g: dequantize_int8(qm.q, qm.scales, g.shape),
            state.mu, updates, is_leaf=is_qm,
        )
        nu_f = jax.tree.map(
            lambda qm, g: dequantize_pos_log(qm.q, qm.scales, g.shape),
            state.nu, updates, is_leaf=is_qm,
        )
        mu_f = jax.tree.map(
            lambda m, g: b1 * m + (1 - b1) * g, mu_f, updates
        )
        nu_f = jax.tree.map(
            lambda v, g: b2 * v + (1 - b2) * g * g, nu_f, updates
        )
        mu_hat = jax.tree.map(lambda m: m / (1 - b1 ** count), mu_f)
        nu_hat = jax.tree.map(lambda v: v / (1 - b2 ** count), nu_f)
        new_updates = jax.tree.map(
            lambda m, v: m / (jnp.sqrt(v) + eps), mu_hat, nu_hat
        )
        # per-step seed (traced) keeps stochastic rounding unbiased across
        # steps; quantize_int8 accepts a traced seed under jit.
        mu_leaves, mu_def = jax.tree.flatten(mu_f)
        mu_q = jax.tree.unflatten(mu_def, [
            QuantizedMoment(*quantize_int8(
                leaf, seed=count * 7919 + i, stochastic=True
            )[:2])
            for i, leaf in enumerate(mu_leaves)
        ])
        nu_q = jax.tree.map(
            lambda v: QuantizedMoment(*quantize_pos_log(v)), nu_f
        )
        return new_updates, ScaleByAdam8bitState(
            count=count, mu=mu_q, nu=nu_q
        )

    return optax.GradientTransformation(init_fn, update_fn)


def adam8bit(
    learning_rate: float | optax.Schedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    fused: bool = False,
    clip_norm: float | None = None,
) -> optax.GradientTransformation:
    """8-bit AdamW (decoupled weight decay on top of quantized moments).

    ``fused=True`` (the ``Strategy.fused_optim`` lever) returns the
    one-pass variant (ops/fused_optim.py): decode, clip, EMA, update
    and re-encode of BOTH moments run in a single Pallas dispatch over
    the flattened leaves instead of a per-leaf kernel chain — same
    state semantics within the documented quantization tolerance.
    ``clip_norm`` fuses optax.clip_by_global_norm into the same pass
    (also honored unfused, as a chained transform).
    """
    if fused:
        from dlrover_tpu.ops.fused_optim import fused_adamw

        return fused_adamw(
            learning_rate, b1=b1, b2=b2, eps=eps,
            weight_decay=weight_decay, clip_norm=clip_norm, bits=8,
        )
    tx = []
    if clip_norm is not None:
        tx.append(optax.clip_by_global_norm(clip_norm))
    tx.append(scale_by_adam8bit(b1=b1, b2=b2, eps=eps))
    if weight_decay:
        tx.append(optax.add_decayed_weights(weight_decay))
    tx.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*tx)
