"""muP (Maximal Update Parametrization) scaling helpers.

Equivalent capability: reference atorch/atorch/mup/ — width-transfer
hyperparameters: tune on a small model, scale width, keep the optimum.
TPU redesign: muP here is two pure functions over the params pytree +
its logical axes (the same contract auto_accelerate uses), plus an optax
wrapper that applies per-leaf learning-rate multipliers — no module
wrapping, composes with any strategy.

Rules implemented (Tensor Programs V, Adam variant):
- "hidden" weights (both dims scale with width, e.g. embed x mlp):
  lr multiplier 1/width_mult, init scale 1/sqrt(width_mult);
- output/readout layers (hidden -> vocab/logits): lr 1/width_mult and
  init scaled by 1/width_mult;
- input embeddings, biases, norms (at most one width dim): unchanged.
Width classification comes from the logical axis names.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax

# logical axis names whose size scales with model width
WIDTH_AXES = frozenset({"embed", "mlp", "heads", "kv_heads", "head_dim"})
# axis names marking the readout dimension
OUTPUT_AXES = frozenset({"vocab", "logits"})


def _classify(axes: tuple | None) -> str:
    """'hidden' | 'output' | 'input' from a leaf's logical axes."""
    if not axes:
        return "input"
    names = [a for a in axes if a]
    width = sum(1 for a in names if a in WIDTH_AXES)
    has_out = any(a in OUTPUT_AXES for a in names)
    if has_out and width >= 1:
        # embed x vocab: output when width feeds the readout (vocab
        # last); input embedding when vocab is the leading (lookup) dim
        return "output" if names[-1] in OUTPUT_AXES else "input"
    if width >= 2:
        return "hidden"
    return "input"


def mup_lr_multipliers(param_logical_axes: Any,
                       width_mult: float) -> Any:
    """Per-leaf lr multipliers for the muP Adam rules."""
    is_axes = lambda x: isinstance(x, tuple) or x is None  # noqa: E731

    def mult(axes):
        kind = _classify(axes)
        if kind in ("hidden", "output"):
            return 1.0 / width_mult
        return 1.0

    return jax.tree.map(mult, param_logical_axes, is_leaf=is_axes)


def mup_rescale_init(params: Any, param_logical_axes: Any,
                     width_mult: float) -> Any:
    """Rescale a standard init to muP at width ``width_mult`` x base."""
    is_axes = lambda x: isinstance(x, tuple) or x is None  # noqa: E731
    flat_axes = jax.tree.leaves(
        param_logical_axes, is_leaf=is_axes
    )
    flat_params, treedef = jax.tree.flatten(params)
    out = []
    for p, axes in zip(flat_params, flat_axes):
        kind = _classify(axes)
        if kind == "hidden":
            out.append(p / jnp.sqrt(width_mult))
        elif kind == "output":
            out.append(p / width_mult)
        else:
            out.append(p)
    return jax.tree.unflatten(treedef, out)


def scale_by_mup(param_logical_axes: Any,
                 width_mult: float) -> optax.GradientTransformation:
    """Optax transform applying muP per-leaf lr multipliers; chain it
    after the base optimizer: ``optax.chain(optax.adam(lr),
    scale_by_mup(axes, width_mult))``."""
    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        mults = mup_lr_multipliers(param_logical_axes, width_mult)
        flat_m = jax.tree.leaves(mults)
        flat_u, treedef = jax.tree.flatten(updates)
        scaled = [u * m for u, m in zip(flat_u, flat_m)]
        return jax.tree.unflatten(treedef, scaled), state

    return optax.GradientTransformation(init_fn, update_fn)


def mup_adam(learning_rate, param_logical_axes, width_mult: float,
             **adam_kwargs) -> optax.GradientTransformation:
    """Adam with muP lr rules baked in."""
    return optax.chain(
        optax.scale_by_adam(**adam_kwargs),
        scale_by_mup(param_logical_axes, width_mult),
        optax.scale_by_learning_rate(learning_rate),
    )
