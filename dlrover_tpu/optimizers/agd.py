"""AGD: auto-switchable optimizer preconditioned by gradient differences.

Equivalent capability: reference atorch/atorch/optimizers/agd.py:18
("AGD: an Auto-switchable Optimizer using Stepwise Gradient Difference
for Preconditioning", NeurIPS 2023). The second moment accumulates the
squared *difference of successive bias-corrected first moments*
(reference agd.py:119-131: ``update = m_t/bc1_t - m_{t-1}/bc1_{t-1}``,
``nu += (1-b2) * update^2``) — an approximation of the diagonal Hessian
— and the update auto-switches between SGD-like (where sqrt(nu_hat) is
clamped at delta) and adaptive behavior.

Implemented as an optax GradientTransformation; state is a pytree so it
shards like the params under GSPMD (each device preconditions its own
FSDP shard — no extra communication). The previous bias-corrected
moment is recomputed from the stored ``mu`` and the step count, so no
extra state slot is needed for it.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class ScaleByAgdState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates      # first moment of gradients
    nu: optax.Updates      # second moment of moment differences
    max_nu: optax.Updates  # amsgrad accumulator (empty tuple if disabled)


def scale_by_agd(
    b1: float = 0.9,
    b2: float = 0.999,
    delta: float = 1e-5,
    amsgrad: bool = False,
    clip: Optional[float] = None,
) -> optax.GradientTransformation:
    """Core AGD scaling (no lr / weight decay).

    Matches the reference dynamics: with ``bc_i = 1 - b_i**t``,
    ``diff_t = mu_t/bc1_t - mu_{t-1}/bc1_{t-1}`` (just ``mu_1/bc1_1`` at
    t=1), ``nu_t = b2*nu_{t-1} + (1-b2)*diff_t**2``, and the update is
    ``(mu_t/bc1_t) / max(sqrt(nu_t/bc2_t), delta)`` — the clamp at
    ``delta`` is the SGD-like/adaptive auto-switch (no extra eps; the
    reference clamps ``sqrt(nu_t)`` at ``delta*sqrt(bc2_t)``, which is
    the same after dividing through by ``sqrt(bc2_t)``).
    """

    def init_fn(params):
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)  # noqa: E731
        return ScaleByAgdState(
            count=jnp.zeros((), jnp.int32),
            mu=zeros(),
            nu=zeros(),
            # no param-sized slot unless amsgrad actually needs it
            max_nu=zeros() if amsgrad else (),
        )

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        countf = count.astype(jnp.float32)
        bc1 = 1 - b1 ** countf
        bc1_old = 1 - b1 ** (countf - 1)  # 0 at the first step
        bc2 = 1 - b2 ** countf
        mu = optax.incremental_update(updates, state.mu, 1 - b1)
        # diff of bias-corrected first moments; at t=1 the previous
        # moment term is dropped (reference agd.py:125-129)
        diff = jax.tree.map(
            lambda m, m_old: jnp.where(
                count == 1,
                m / bc1,
                m / bc1 - m_old / jnp.maximum(bc1_old, 1e-38),
            ),
            mu, state.mu,
        )
        nu = jax.tree.map(
            lambda n, d: b2 * n + (1 - b2) * d * d, state.nu, diff
        )
        if amsgrad:
            max_nu = jax.tree.map(jnp.maximum, state.max_nu, nu)
            denom_nu = max_nu
        else:
            max_nu = ()
            denom_nu = nu
        # auto-switch: where sqrt(nu_hat) < delta the denominator clamps
        # to delta, giving constant (SGD-like) scaling; elsewhere the
        # adaptive preconditioner applies.
        new_updates = jax.tree.map(
            lambda m, n: m / bc1 / jnp.maximum(jnp.sqrt(n / bc2), delta),
            mu, denom_nu,
        )
        if clip is not None:
            new_updates = jax.tree.map(
                lambda u: jnp.clip(u, -clip, clip), new_updates
            )
        return new_updates, ScaleByAgdState(
            count=count, mu=mu, nu=nu, max_nu=max_nu
        )

    return optax.GradientTransformation(init_fn, update_fn)


def agd(
    learning_rate: float | optax.Schedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    delta: float = 1e-5,
    weight_decay: float = 0.0,
    amsgrad: bool = False,
    clip: Optional[float] = None,
) -> optax.GradientTransformation:
    """AGD with decoupled (AdamW-style) weight decay."""
    tx = [scale_by_agd(b1=b1, b2=b2, delta=delta, amsgrad=amsgrad,
                       clip=clip)]
    if weight_decay:
        tx.append(optax.add_decayed_weights(weight_decay))
    tx.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*tx)
