"""AGD: auto-switchable optimizer preconditioned by gradient differences.

Equivalent capability: reference atorch/atorch/optimizers/agd.py:18
("AGD: an Auto-switchable Optimizer using Stepwise Gradient Difference
for Preconditioning", NeurIPS 2023). The second moment accumulates the
*difference* between successive gradients instead of the raw gradient —
an approximation of the diagonal Hessian — and the update auto-switches
between SGD-like (where sqrt(v̂) < delta) and adaptive behavior.

Implemented as an optax GradientTransformation; state is a pytree so it
shards like the params under GSPMD (each device preconditions its own
FSDP shard — no extra communication).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class ScaleByAgdState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates      # first moment of gradients
    nu: optax.Updates      # second moment of gradient differences
    prev_grad: optax.Updates


def scale_by_agd(
    b1: float = 0.9,
    b2: float = 0.999,
    delta: float = 1e-5,
    eps: float = 1e-8,
) -> optax.GradientTransformation:
    """Core AGD scaling (no lr / weight decay)."""

    def init_fn(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return ScaleByAgdState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(jnp.zeros_like, params),
            nu=jax.tree.map(jnp.zeros_like, params),
            prev_grad=zeros,
        )

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        # first step: the "difference" is the gradient itself (reference
        # initializes the diff accumulator from g_1)
        diff = jax.tree.map(
            lambda g, pg: jnp.where(count == 1, g, g - pg),
            updates, state.prev_grad,
        )
        mu = optax.incremental_update(updates, state.mu, 1 - b1)
        nu = jax.tree.map(
            lambda n, d: b2 * n + (1 - b2) * d * d, state.nu, diff
        )
        mu_hat = jax.tree.map(lambda m: m / (1 - b1 ** count), mu)
        nu_hat = jax.tree.map(lambda n: n / (1 - b2 ** count), nu)
        # auto-switch: where sqrt(nu_hat) < delta the denominator clamps
        # to delta, giving constant (SGD-like) scaling; elsewhere the
        # adaptive preconditioner applies.
        new_updates = jax.tree.map(
            lambda m, n: m / jnp.maximum(jnp.sqrt(n) + eps, delta),
            mu_hat, nu_hat,
        )
        return new_updates, ScaleByAgdState(
            count=count, mu=mu, nu=nu, prev_grad=updates
        )

    return optax.GradientTransformation(init_fn, update_fn)


def agd(
    learning_rate: float | optax.Schedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    delta: float = 1e-5,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """AGD with decoupled (AdamW-style) weight decay."""
    tx = [scale_by_agd(b1=b1, b2=b2, delta=delta, eps=eps)]
    if weight_decay:
        tx.append(optax.add_decayed_weights(weight_decay))
    tx.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*tx)
