from dlrover_tpu.optimizers.agd import agd, scale_by_agd
from dlrover_tpu.optimizers.wsam import (
    make_wsam_grad_fn,
    make_wsam_step_fn,
    wsam_update,
)
from dlrover_tpu.optimizers.low_bit import adam8bit, scale_by_adam8bit
from dlrover_tpu.ops.fused_optim import (
    FusedAdam8bitState,
    FusedAdamState,
    fused_adamw,
)
from dlrover_tpu.optimizers.offload import OffloadAdam, OffloadAdamState
from dlrover_tpu.optimizers.group_sparse import group_adagrad, group_adam
from dlrover_tpu.optimizers.mup import (
    mup_adam,
    mup_lr_multipliers,
    mup_rescale_init,
    scale_by_mup,
)

__all__ = [
    "agd",
    "scale_by_agd",
    "make_wsam_grad_fn",
    "make_wsam_step_fn",
    "wsam_update",
    "adam8bit",
    "scale_by_adam8bit",
    "fused_adamw",
    "FusedAdamState",
    "FusedAdam8bitState",
    "OffloadAdam",
    "OffloadAdamState",
    "group_adam",
    "group_adagrad",
    "mup_adam",
    "mup_lr_multipliers",
    "mup_rescale_init",
    "scale_by_mup",
]
