from dlrover_tpu.optimizers.agd import agd, scale_by_agd
from dlrover_tpu.optimizers.wsam import make_wsam_grad_fn, wsam_update
from dlrover_tpu.optimizers.low_bit import adam8bit, scale_by_adam8bit
from dlrover_tpu.optimizers.group_sparse import group_adagrad, group_adam

__all__ = [
    "agd",
    "scale_by_agd",
    "make_wsam_grad_fn",
    "wsam_update",
    "adam8bit",
    "scale_by_adam8bit",
    "group_adam",
    "group_adagrad",
]
