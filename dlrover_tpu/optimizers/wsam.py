"""WSAM: sharpness-aware minimization with a weighted sharpness term.

Equivalent capability: reference atorch/atorch/optimizers/wsam.py:11
(`WeightedSAM`, KDD 2023). The loss is regularized by weighted sharpness
``L + gamma/(1-gamma) * (L(w+eps) - L(w))``. With ``alpha =
gamma/(1-gamma)`` (the reference's weighting, wsam.py:45), the coupled
gradient fed to the base optimizer is ``g + alpha*(g_adv - g)``; the
reference's *default* mode is decoupled (wsam.py:34 ``decouple=True``),
where the base optimizer steps with the plain gradient and the
sharpness term ``alpha*(g_adv - g)`` is applied directly to the weights
scaled by the learning rate (wsam.py:98-105) — outside the base
optimizer's adaptive preconditioning.

TPU-first: SAM needs two forward/backward passes per step. Instead of an
optimizer class that closes over a closure (the torch pattern), we
expose :func:`make_wsam_grad_fn` (coupled gradient inside one jitted
program) and :func:`make_wsam_step_fn` (full decoupled update step) —
XLA schedules both passes back-to-back and GSPMD shards both
identically, so the whole thing runs under the same mesh with no extra
host round-trips.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax


def _global_norm(tree) -> jnp.ndarray:
    return jnp.sqrt(sum(
        jnp.sum(jnp.square(x)) for x in jax.tree.leaves(tree)
    ))


def wsam_update(grads, adv_grads, gamma: float = 0.9):
    """Coupled WSAM gradient ``g + alpha*(g_adv - g)``, alpha=gamma/(1-gamma).

    gamma=0 -> plain gradient; gamma=0.5 (alpha=1) -> pure SAM gradient;
    the reference's default gamma=0.9 (alpha=9) over-weights the
    sharpness term. Matches reference wsam.py:91-92
    (``grad*alpha + plain*(1-alpha)`` with their alpha = our 1-alpha
    convention resolved: both give ``g + alpha*(g_adv-g)``).
    """
    if gamma >= 1.0:
        raise ValueError(f"gamma must be < 1, got {gamma}")
    alpha = gamma / (1.0 - gamma)
    return jax.tree.map(
        lambda g, ga: g + alpha * (ga - g), grads, adv_grads
    )


def _perturb(params, grads, rho: float, adaptive: bool, eps: float):
    gnorm = _global_norm(grads)
    scale = rho / (gnorm + eps)
    if adaptive:
        return jax.tree.map(
            lambda p, g: p + jnp.square(p) * g * scale, params, grads
        )
    return jax.tree.map(lambda p, g: p + scale * g, params, grads)


def make_wsam_grad_fn(
    loss_fn: Callable,
    rho: float = 0.05,
    gamma: float = 0.9,
    has_aux: bool = False,
    adaptive: bool = False,
    sam_eps: float = 1e-12,
) -> Callable:
    """Returns ``grad_fn(params, batch, rng) -> (loss, grads)`` computing
    the *coupled* WSAM direction (two passes fused into the caller's
    jit). For the reference's default decoupled behavior use
    :func:`make_wsam_step_fn`.
    """
    grad = jax.value_and_grad(loss_fn, has_aux=has_aux)

    def wsam_grad(params, batch, rng):
        out, grads = grad(params, batch, rng)
        perturbed = _perturb(params, grads, rho, adaptive, sam_eps)
        _, adv_grads = grad(perturbed, batch, rng)
        return out, wsam_update(grads, adv_grads, gamma)

    return wsam_grad


def make_wsam_step_fn(
    loss_fn: Callable,
    base_tx: optax.GradientTransformation,
    learning_rate,
    rho: float = 0.05,
    gamma: float = 0.9,
    decouple: bool = True,
    adaptive: bool = False,
    has_aux: bool = False,
    sam_eps: float = 1e-12,
) -> Callable:
    """Full WSAM step in the reference's default *decoupled* mode.

    Returns ``step(params, opt_state, batch, rng, step=None) ->
    (params, opt_state, out)``. Decoupled: the base optimizer consumes
    the plain gradient, then the weighted sharpness ``alpha*(g_adv -
    g)`` is subtracted from the weights scaled by the learning rate
    (reference wsam.py:98-105). ``decouple=False`` feeds the coupled
    blend to the base optimizer.

    ``learning_rate`` may be a float or an optax schedule; a schedule
    requires passing the current ``step`` so the decoupled sharpness
    term tracks the base optimizer's decayed lr (the reference reads
    the group's current lr each step).
    """
    if gamma >= 1.0:
        raise ValueError(f"gamma must be < 1, got {gamma}")
    alpha = gamma / (1.0 - gamma)
    grad = jax.value_and_grad(loss_fn, has_aux=has_aux)

    def step(params, opt_state, batch, rng, step=None):
        if callable(learning_rate):
            if step is None:
                raise ValueError(
                    "learning_rate is a schedule: pass the current "
                    "step to make_wsam_step_fn's step(..., step=...)"
                )
            lr = learning_rate(step)
        else:
            lr = learning_rate
        out, grads = grad(params, batch, rng)
        perturbed = _perturb(params, grads, rho, adaptive, sam_eps)
        _, adv_grads = grad(perturbed, batch, rng)
        if decouple:
            updates, opt_state2 = base_tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(updates=jax.tree.map(
                lambda u, g, ga: u - lr * alpha * (ga - g),
                updates, grads, adv_grads,
            ), params=params)
        else:
            blended = wsam_update(grads, adv_grads, gamma)
            updates, opt_state2 = base_tx.update(
                blended, opt_state, params
            )
            new_params = optax.apply_updates(params, updates)
        return new_params, opt_state2, out

    return step
