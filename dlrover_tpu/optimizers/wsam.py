"""WSAM: sharpness-aware minimization with a weighted sharpness term.

Equivalent capability: reference atorch/atorch/optimizers/wsam.py:11
(`WeightedSAM`, KDD 2023). The loss is regularized by weighted sharpness
``L + gamma/(1-gamma) * (L(w+eps) - L(w))``; the gradient is a blend of
the plain gradient and the SAM (perturbed) gradient.

TPU-first: SAM needs two forward/backward passes per step. Instead of an
optimizer class that closes over a closure (the torch pattern), we expose
:func:`make_wsam_grad_fn`, which turns any ``loss_fn(params, batch, rng)``
into a gradient function computing the WSAM direction *inside one jitted
program* — XLA schedules both passes back-to-back and GSPMD shards both
identically, so the whole thing runs under the same mesh with no extra
host round-trips.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def _global_norm(tree) -> jnp.ndarray:
    return jnp.sqrt(sum(
        jnp.sum(jnp.square(x)) for x in jax.tree.leaves(tree)
    ))


def wsam_update(grads, adv_grads, gamma: float = 0.9):
    """Blend plain + perturbed gradients with sharpness weight gamma.

    gamma=0 -> plain gradient (SGD); gamma=1 -> pure SAM gradient;
    the reference's default gamma ~0.9 emphasizes the sharpness term as
    ``g + gamma/(1-gamma) * (g_adv - g)`` normalized by 1/(1-gamma),
    i.e. ``(1-gamma)*g + gamma*g_adv``.
    """
    return jax.tree.map(
        lambda g, ga: (1.0 - gamma) * g + gamma * ga, grads, adv_grads
    )


def make_wsam_grad_fn(
    loss_fn: Callable,
    rho: float = 0.05,
    gamma: float = 0.9,
    has_aux: bool = False,
) -> Callable:
    """Returns ``grad_fn(params, batch, rng) -> (loss, grads)`` computing
    the WSAM direction (two passes fused into the caller's jit).
    """
    grad = jax.value_and_grad(loss_fn, has_aux=has_aux)

    def wsam_grad(params, batch, rng):
        out, grads = grad(params, batch, rng)
        gnorm = _global_norm(grads)
        scale = rho / (gnorm + 1e-12)
        perturbed = jax.tree.map(lambda p, g: p + scale * g, params, grads)
        _, adv_grads = grad(perturbed, batch, rng)
        return out, wsam_update(grads, adv_grads, gamma)

    return wsam_grad
