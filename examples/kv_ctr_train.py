"""Sparse (CTR-style) training example: dynamic embeddings + GroupAdam.

Equivalent capability: the reference's TFPlus sparse path (KvVariable +
group optimizers) used for recommendation workloads.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax


def main():
    p = argparse.ArgumentParser("kv_ctr_train")
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--capacity", type=int, default=1 << 12)
    p.add_argument("--evict-every", type=int, default=100)
    args = p.parse_args()

    from dlrover_tpu import trainer as tpu_trainer

    tpu_trainer.init_distributed()

    from dlrover_tpu.ops.sparse_embedding import KvEmbedding
    from dlrover_tpu.optimizers import group_adam

    kv = KvEmbedding(dim=args.dim, capacity=args.capacity)
    params = {
        "table": kv.init_table(jax.random.key(0)),
        "w": jnp.zeros((args.dim, 1)),
    }
    opt = group_adam(1e-2)
    opt_state = opt.init(params)
    rs = np.random.RandomState(0)

    @jax.jit
    def step(params, opt_state, slots, labels):
        def loss_fn(p):
            vecs = KvEmbedding.embed(p["table"], slots)  # [B, F, D]
            pooled = jnp.mean(vecs, axis=1)
            logits = (pooled @ p["w"]).squeeze(-1)
            return jnp.mean(
                optax.sigmoid_binary_cross_entropy(logits, labels)
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    for i in range(args.steps):
        # power-law feature ids: a hot head plus a long cold tail
        raw_ids = (rs.pareto(1.2, size=(64, 8)) * 50).astype(np.int64)
        labels = jnp.asarray(
            (raw_ids.sum(axis=1) % 2).astype(np.float32)
        )
        slots = jnp.asarray(kv.lookup_slots(raw_ids))
        params, opt_state, loss = step(params, opt_state, slots, labels)
        if (i + 1) % args.evict_every == 0:
            params["table"] = kv.evict(params["table"], threshold=2)
            print(
                f"step {i+1}: loss={float(loss):.4f} "
                f"live_ids={len(kv.mapper)}"
            )
    ids, vecs, freqs = kv.export(params["table"], min_frequency=2)
    print(f"exported {len(ids)} warm embeddings")


if __name__ == "__main__":
    main()
