"""Coworker preprocessing pipeline example.

CPU pods run heavy preprocessing (tokenisation, augmentation) through
:class:`CoworkerDataService`; trainer pods consume finished batches via
:class:`CoworkerDataset`. This is the reference's coworker economics
(atorch coworker_data_service): the accelerator never waits on Python
preprocessing because it happens on cheap CPU pods.

Single-machine demo (each role is its own process in production):

    python examples/coworker_pipeline.py

Production layout:
- trainer rank 0 starts ``DataInfoService(port=...)`` and exports the
  address (e.g. through the master kv-store);
- each CPU pod runs ``CoworkerDataService(make_iter,
  announce_to=info_addr, advertise_host=<pod_ip>)``;
- every trainer rank iterates ``CoworkerDataset(info_addr,
  n_batches=steps)``.
"""

import numpy as np

from dlrover_tpu.trainer.elastic.coworker import (
    CoworkerDataService,
    CoworkerDataset,
    DataInfoService,
)

VOCAB = 1000
BATCH, SEQ = 8, 128


def make_preprocessing_iter():
    """Stand-in for expensive CPU work (tokenise, pack, augment)."""
    rng = np.random.RandomState(0)
    while True:
        # pretend this cost real CPU time
        tokens = rng.randint(0, VOCAB, (BATCH, SEQ + 1), dtype=np.int64)
        yield {"tokens": tokens}


def main():
    # --- trainer rank 0: announcement queue
    info = DataInfoService()
    info.start()

    # --- CPU pods: two preprocessing workers
    coworkers = [
        CoworkerDataService(
            make_preprocessing_iter,
            announce_to=info.addr,
            announce_every=2,
            queue_size=8,
        )
        for _ in range(2)
    ]
    for cw in coworkers:
        cw.start()

    # --- trainer: consume 20 training batches
    try:
        dataset = CoworkerDataset(info.addr, n_batches=20, prefetch=4)
        for step, batch in enumerate(dataset):
            # feed res.train_step(state, batch, rng) here
            assert batch["tokens"].shape == (BATCH, SEQ + 1)
            if step % 5 == 0:
                print(f"step {step}: batch ready "
                      f"(first id {int(batch['tokens'][0, 0])})")
        stats = [cw.stats for cw in coworkers]
        print("done; coworker stats:", stats)
    finally:
        for cw in coworkers:
            cw.stop()
        info.stop()


if __name__ == "__main__":
    main()
