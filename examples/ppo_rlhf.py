"""PPO (RLHF-style) example over the ModelEngine.

Equivalent capability: reference atorch/atorch/rl — actor/critic/ref
models each with their own strategy, experience generation + PPO update.
The "reward model" here is programmatic; swap in a learned model by
registering a trainable "reward" ModelSpec.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax


def main():
    p = argparse.ArgumentParser("ppo_rlhf")
    p.add_argument("--iterations", type=int, default=20)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--horizon", type=int, default=4)
    args = p.parse_args()

    from dlrover_tpu import trainer as tpu_trainer

    tpu_trainer.init_distributed()

    from dlrover_tpu.rl import ModelEngine, ModelSpec, PPOConfig, PPOTrainer

    n_actions, obs_dim, hidden = 4, 8, 64

    def actor_init(rng):
        k1, k2 = jax.random.split(rng)
        return {
            "w1": jax.random.normal(k1, (obs_dim, hidden)) * 0.1,
            "w2": jax.random.normal(k2, (hidden, n_actions)) * 0.1,
        }

    def actor_apply(params, obs):
        return jnp.tanh(obs @ params["w1"]) @ params["w2"]

    def critic_init(rng):
        return {"w": jax.random.normal(rng, (obs_dim, 1)) * 0.1}

    def critic_apply(params, obs):
        return (obs @ params["w"]).squeeze(-1)

    engine = ModelEngine({
        "actor": ModelSpec(actor_init, actor_apply, trainable=True,
                           optimizer=optax.adam(3e-3)),
        "critic": ModelSpec(critic_init, critic_apply, trainable=True,
                            optimizer=optax.adam(3e-3)),
        "ref": ModelSpec(actor_init, actor_apply),
    })
    engine.sync_ref_from_actor()

    def score_fn(obs, actions):
        target = jnp.argmax(obs[..., :n_actions], axis=-1)
        return jnp.mean((actions == target).astype(jnp.float32), axis=-1)

    trainer = PPOTrainer(
        engine,
        PPOConfig(ppo_epochs=4, train_batch_size=16, kl_coef=0.02),
        score_fn=score_fn,
    )
    rs = np.random.RandomState(0)

    def prompts():
        obs = np.zeros((args.batch, args.horizon, obs_dim), np.float32)
        idx = rs.randint(0, n_actions, size=(args.batch, args.horizon))
        for b in range(args.batch):
            for t in range(args.horizon):
                obs[b, t, idx[b, t]] = 1.0
        return {"obs": obs}

    for it in range(args.iterations):
        trainer.buffer.reset()
        mean_score = trainer.make_experience(prompts())
        stats = trainer.rl_training()
        if (it + 1) % 5 == 0:
            print(
                f"iter {it+1}: score={mean_score:.3f} "
                f"kl={float(stats['approx_kl']):.4f}"
            )
    final = trainer.make_experience(prompts())
    print(f"final mean score: {final:.3f}")


if __name__ == "__main__":
    main()
