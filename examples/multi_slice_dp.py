"""Multi-slice training: hybrid ICI x DCN mesh with cross-process DP.

The reference scales past one node with nested cross-node NCCL process
groups (atorch/atorch/distributed/distributed.py:321-427). TPU-native
equivalent: ONE hybrid mesh whose DCN-tolerant axes (here ``data``)
stride across slice boundaries while fsdp/tensor/seq stay inside each
slice's ICI domain — XLA routes each collective over the right fabric.

Run 2 simulated "slices" on one machine (each a jax.distributed process
with 4 virtual CPU devices):

    python examples/multi_slice_dp.py            # parent: spawns both
    # or by hand, one process per slice:
    python examples/multi_slice_dp.py --process-id 0 --port 12345 &
    python examples/multi_slice_dp.py --process-id 1 --port 12345

On real multi-slice TPU the same MeshConfig works unchanged: devices
carry ``slice_index`` and ``mesh_utils.create_hybrid_device_mesh`` lays
the slices out over DCN.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

N_PROCS = 2
DEVICES_PER_PROC = 4


def worker(process_id: int, port: int) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", DEVICES_PER_PROC)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=N_PROCS,
        process_id=process_id,
    )

    import jax.numpy as jnp
    import numpy as np
    import optax

    from dlrover_tpu.models import (
        PRESETS,
        llama_init,
        llama_logical_axes,
        llama_loss_fn,
    )
    from dlrover_tpu.parallel import MeshConfig, Strategy, auto_accelerate

    config = PRESETS["tiny"]
    # data axis spans the slices (dcn_data=2): the once-per-step
    # gradient allreduce is the only cross-slice traffic; fsdp's
    # per-step param all-gathers stay inside each slice
    strategy = Strategy(
        mesh=MeshConfig(
            data=N_PROCS, fsdp=DEVICES_PER_PROC, dcn_data=N_PROCS
        ),
        compute_dtype="bfloat16",
        remat="none",
        donate=False,
    )
    res = auto_accelerate(
        llama_loss_fn(config),
        lambda rng: llama_init(config, rng),
        optax.adamw(1e-3),
        llama_logical_axes(config),
        strategy=strategy,
    )
    state = res.state
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(
            0, config.vocab_size, (N_PROCS * DEVICES_PER_PROC, 65)
        )
    )
    for step in range(3):
        state, metrics = res.train_step(
            state, {"tokens": tokens}, jax.random.key(step)
        )
        if process_id == 0:
            print(f"step {step}: loss {float(metrics['loss']):.4f}",
                  flush=True)
    print(f"slice {process_id}: done", flush=True)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--port", type=int, default=0)
    args = p.parse_args()
    if args.process_id is not None:
        worker(args.process_id, args.port)
        return
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--process-id", str(i), "--port", str(port)],
            env=env,
        )
        for i in range(N_PROCS)
    ]
    try:
        rcs = [q.wait(timeout=600) for q in procs]
    finally:
        # a dead sibling leaves the survivor blocked in a collective:
        # never orphan it
        for q in procs:
            if q.poll() is None:
                q.kill()
    if any(rcs):
        raise SystemExit(f"worker exit codes {rcs}")
    print("multi-slice example ok")


if __name__ == "__main__":
    main()
