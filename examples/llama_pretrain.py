"""Llama pretraining example: the full elastic stack in one script.

Equivalent capability: reference atorch/examples/llama2 (FSDP/3D-parallel
Llama-2 pretraining scripts) and examples/pytorch/ (elastic training
with dlrover-run).

Run single-host (a local master is spawned automatically):

    tpu-run --nnodes 1 --nproc_per_node 1 --auto-tunning \
        examples/llama_pretrain.py --preset nano-350m --steps 200

Multi-host (one command per host, DLROVER_MASTER_ADDR pointing at the
job master):

    tpu-run --nnodes 4 --node_rank $RANK --network-check \
        examples/llama_pretrain.py --preset llama2-7b

What this shows, end to end:
- master-coordinated rendezvous -> jax.distributed init (init_distributed)
- auto_strategy / search_strategy -> auto_accelerate sharded train step
- elastic dataloader with mid-epoch checkpoint/resume across world-size
  changes (swap in ElasticDataset for master-served shard assignment)
- Flash Checkpoint: async shm saves every --save-steps, storage persist,
  automatic resume after restarts
- runtime metrics + step timing flowing to the agent/master
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np


def parse_args():
    p = argparse.ArgumentParser("llama_pretrain")
    p.add_argument("--preset", default="nano-350m")
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--optimizer", default="adamw",
                   choices=["adamw", "sgd", "agd", "adam8bit"])
    p.add_argument("--output-dir", default="/tmp/llama_pretrain")
    p.add_argument("--save-steps", type=int, default=50)
    p.add_argument("--search-strategy", action="store_true",
                   help="measured strategy search instead of heuristics")
    return p.parse_args()


def synthetic_token_stream(vocab_size: int, seq_len: int, n_samples: int):
    """Stand-in corpus: replace with your tokenized dataset."""

    class DS:
        def __len__(self):
            return n_samples

        def __getitem__(self, idx):
            rng = np.random.RandomState(idx)
            return rng.randint(
                0, vocab_size, size=(seq_len + 1,), dtype=np.int32
            )

    return DS()


def main():
    args = parse_args()

    from dlrover_tpu import trainer as tpu_trainer

    tpu_trainer.init_distributed()

    from dlrover_tpu.models import (
        PRESETS,
        llama_init,
        llama_logical_axes,
        llama_loss_fn,
    )
    from dlrover_tpu.parallel import auto_strategy
    from dlrover_tpu.trainer import Trainer, TrainingArgs
    from dlrover_tpu.trainer.elastic import (
        ElasticDataLoader,
        ElasticSampler,
    )

    config = PRESETS[args.preset]
    n_devices = jax.device_count()
    strategy = auto_strategy(
        n_devices,
        param_count=config.param_count(),
        seq_len=args.seq_len,
        devices_per_host=max(len(jax.local_devices()), 1),
    )

    dataset = synthetic_token_stream(
        config.vocab_size, args.seq_len, n_samples=1 << 16
    )
    loader = ElasticDataLoader(
        dataset,
        batch_size=args.batch_size,
        sampler=ElasticSampler(
            len(dataset),
            num_replicas=max(tpu_trainer.world_size(), 1),
            rank=tpu_trainer.global_rank(),
            shuffle=True,
        ),
        collate_fn=lambda rows: {"tokens": np.stack(rows)},
    )

    trainer = Trainer(
        llama_loss_fn(config),
        lambda rng: llama_init(config, rng),
        llama_logical_axes(config),
        TrainingArgs(
            output_dir=args.output_dir,
            max_steps=args.steps,
            num_epochs=1_000_000,  # run the step budget out
            learning_rate=args.lr,
            optimizer=args.optimizer,
            strategy=strategy,
            flash_checkpoint=True,
            save_steps=args.save_steps,
            log_steps=10,
        ),
        train_data=loader,
    )
    state, metrics = trainer.train()
    loss = float(metrics.get("loss", jnp.nan))
    print(f"done: step={trainer.global_step} loss={loss:.4f}")
    trainer.close()


if __name__ == "__main__":
    main()
