"""Long-context training with ring attention over a ``seq`` mesh axis.

The sequence dimension is sharded across devices; each device keeps its
q shard resident while k/v rotate around the ring (`lax.ppermute`), and
every visiting block runs the packed Pallas flash kernel with dynamic
global-position causal masks (parallel/sequence.py). On hardware the
permutes ride ICI neighbour links; here the virtual CPU mesh
demonstrates the schedule end-to-end — the same code runs unchanged on
a real TPU slice.

Reference capability: atorch DistributedSelfAttention
(distributed_attention.py:79) + its sequence-parallel examples.

Usage::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context_ring.py --steps 3
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=3)
    parser.add_argument("--seq-len", type=int, default=512)
    parser.add_argument("--batch-size", type=int, default=2)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dlrover_tpu.models import (
        llama_init,
        llama_logical_axes,
        llama_loss_fn,
    )
    from dlrover_tpu.models.llama import LlamaConfig
    from dlrover_tpu.parallel import MeshConfig, Strategy, auto_accelerate

    n = len(jax.devices())
    seq_shards = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
    config = LlamaConfig(
        vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        mlp_dim=128, max_seq_len=args.seq_len, attn_impl="flash",
        remat=False, dtype="float32",
    )
    strategy = Strategy(
        mesh=MeshConfig(data=n // seq_shards, seq=seq_shards),
        compute_dtype=None, remat="none",
    )
    res = auto_accelerate(
        llama_loss_fn(config),
        lambda rng: llama_init(config, rng),
        optax.adamw(1e-3),
        llama_logical_axes(config),
        strategy=strategy,
    )
    print(f"mesh: data={n // seq_shards} x seq={seq_shards}, "
          f"sequence {args.seq_len} sharded {args.seq_len // seq_shards}"
          f"/device (ring attention)")

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(
        0, config.vocab_size, (args.batch_size, args.seq_len + 1)))
    state = res.state
    for step in range(args.steps):
        state, metrics = res.train_step(
            state, {"tokens": tokens}, jax.random.key(step))
        print(f"step {step}: loss={float(metrics['loss']):.4f}")
    assert np.isfinite(float(metrics["loss"]))
    print("ring-attention training ok")


if __name__ == "__main__":
    main()
